"""Model configuration.

Replaces the reference's `Alphafold2.__init__` kwarg soup
(reference alphafold2_pytorch/alphafold2.py:329-346) with a frozen dataclass
that is hashable (safe as a jit static argument) and explicit about every
capability flag.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple, Union

import jax.numpy as jnp

from alphafold2_tpu.constants import (
    DISTOGRAM_BUCKETS,
    MAX_NUM_MSA,
    NUM_AMINO_ACIDS,
    NUM_EMBEDDS_TR,
)
from alphafold2_tpu.ops.attention import AttentionConfig


# depth threshold below which the smaller parameter/optimizer state leaves
# ~2 GB of HBM headroom on a 16G chip (PERF.md "where the next factors come
# from" item 1): shallow trunks trade that headroom for fewer, larger
# attention chunks and bigger streaming tiles
_ATTN_HEADROOM_MAX_DEPTH = 24


def depth_aware_attn_defaults(depth: int) -> dict:
    """Measured-headroom attention-knob defaults for the north-star preset.

    At depth <= 24 the trunk's parameter + optimizer state is small enough
    that the memory-bounding chunks can be raised (PERF.md item 1):
    `attn_batch_chunk` 32 -> 96 (3x fewer, 3x larger attention programs
    per pass) and `attn_flash_tile_elems` 2^25 -> 2^26 (halves the
    sequential tile count of the XLA streaming path). Depth 48 keeps the
    proven-to-fit values — the deep config has no headroom to spend.

    This is THE resolver for the two knobs: the training preset
    (training/presets.py) routes through it, so the bench scripts that
    inherit preset defaults (bench.py, scripts/bench_sweep.py legs without
    explicit overrides) measure against it, and the `e2e_chunk32` /
    `e2e_tile25` sweep legs A/B the old values against it on chip.
    """
    if depth <= _ATTN_HEADROOM_MAX_DEPTH:
        return {"attn_batch_chunk": 96, "attn_flash_tile_elems": 1 << 26}
    return {"attn_batch_chunk": 32, "attn_flash_tile_elems": 1 << 25}


@dataclasses.dataclass(frozen=True)
class Alphafold2Config:
    dim: int
    depth: int = 6
    heads: int = 8
    dim_head: int = 64
    max_seq_len: int = 2048
    num_tokens: int = NUM_AMINO_ACIDS
    num_embedds: int = NUM_EMBEDDS_TR
    max_num_msa: int = MAX_NUM_MSA
    num_buckets: int = DISTOGRAM_BUCKETS
    attn_dropout: float = 0.0
    ff_dropout: float = 0.0
    reversible: bool = False
    # jax.checkpoint each trunk layer: O(1) activation memory in depth at
    # ~33% extra FLOPs — the remat sibling of the reversible trunk; works
    # with or without an MSA stream (reversible requires one)
    remat: bool = False
    # rematerialization policy: what the per-layer checkpoint SAVES instead
    # of recomputing. None = save nothing (maximum recompute, minimum
    # memory); "dots" = save all matmul outputs (recompute only elementwise
    # — much cheaper backward, higher residency); "dots_no_batch" = save
    # matmuls without batch dims only (the usual TPU sweet spot). Ignored
    # unless remat=True.
    remat_policy: Optional[str] = None
    # lax.scan the sequential trunk over depth (uniform-sparse-flag runs
    # scan as segments): ONE compiled layer body instead of depth copies —
    # at depth 48 this is the difference between minutes and seconds of
    # XLA compile time. The reversible trunk always scans.
    scan_layers: bool = False
    # bool, or a per-layer tuple of bools (reference cast_tuple semantics,
    # alphafold2.py:25-26,349 — the reference ignores the per-layer value at
    # alphafold2.py:392, a bug; we apply it per layer)
    sparse_self_attn: Union[bool, Tuple[bool, ...]] = False
    sparse_block_size: int = 16
    sparse_num_random_blocks: Optional[int] = None  # None: max_seq_len//block//4
    sparse_num_local_blocks: int = 4
    sparse_num_global_blocks: int = 1
    sparse_layout_seed: int = 0
    # Pallas TPU kernel fast path: True / False / "auto" (kernel for long
    # sequences, XLA block-gather for short — see ops/sparse.py)
    sparse_use_kernel: Union[bool, str] = "auto"
    cross_attn_compress_ratio: int = 1
    # "flat": cross-attention between the fully-flattened pair and MSA
    # streams (reference alphafold2.py:316-317 semantics — O(n^2 * r*c)
    # logits, streamed blockwise at scale). "aligned": column-aligned
    # cross-attention — each pair token attends only the MSA column its grid
    # column maps to, and each MSA token attends only its column's pair-grid
    # block. O(n^2 * r) total: the TPU-first redesign that makes the
    # crop-384 / MSA-128 workload tractable (the pattern the reference built
    # as per-axis context broadcast but never used, alphafold2.py:269-273).
    cross_attn_mode: str = "flat"
    msa_tie_row_attn: bool = False
    # blockwise flash streaming for dense attention: True / False / "auto"
    # (see ops/attention.py AttentionConfig.flash)
    attn_flash: Union[bool, str] = "auto"
    # chunk the folded-batch axis of every dense attention op (QKV/out
    # projections included) into blocks of this many elements (0 = off; see
    # ops/attention.py AttentionConfig.batch_chunk)
    attn_batch_chunk: int = 0
    # XLA flash-streaming tile knobs (AttentionConfig.flash_tile_elems /
    # flash_kv_block): target logit-tile elements and K/V streaming block.
    # Bigger tiles = better MXU utilization, more live memory
    attn_flash_tile_elems: int = 1 << 25
    attn_flash_kv_block: int = 2048
    # Pallas flash-kernel QUERY block-size target (None = auto): each
    # attention shape picks its own unpadded block up to this size (see
    # ops/attention.py AttentionConfig.flash_qb_target)
    attn_flash_qb_target: Optional[int] = None
    # XLA streaming attention: materialize score/probability tiles in the
    # compute dtype instead of f32 (AttentionConfig
    # flash_compute_dtype_logits) — halves the streaming path's dominant
    # HBM traffic under bf16 at ~0.5% probability error
    attn_flash_compute_dtype_logits: bool = False
    # sigmoid output gating on every attention op: out = sigmoid(W_g x) *
    # attn(x) before the output projection (the AF2-style gate the
    # reference omits). Gate weights init to (w=0, b=1) so a freshly
    # gated model starts at sigmoid(1) ~ 0.73 * the ungated output. On
    # the TPU kernel path the gate is applied INSIDE the Pallas flash
    # kernel's finish step (ops/flash_kernel.py fused epilogue — no extra
    # HBM round-trip); off-kernel paths apply it as an epilogue. Changes
    # numerics and the parameter tree: part of the serving config tag.
    attn_gate: bool = False
    # intra-layer trunk schedule (models/trunk.py):
    #   "serial"          — the reference op order, one op after another;
    #   "branch_parallel" — the pair track (self-attn + FF) and MSA track
    #     (self-attn + FF) are expressed as two data-independent branches
    #     that JOIN only at the cross-attention exchange (Parallel
    #     Evoformer, arXiv 2211.00235), marked by an optimization-barrier
    #     join the scheduler (and analysis/schedule_lint.py) can see.
    # Same math either way — branch_parallel only re-groups ops that were
    # already independent — so the arms are allclose fwd + grads; still
    # part of the serving config tag (schedules may differ in fusion-level
    # float association, and bit-exactness pins must not alias).
    trunk_schedule: str = "serial"
    # chunk feed-forward token axes into blocks of this many tokens (0 =
    # off): bounds the GEGLU 8*dim intermediate, which at crop 384 is the
    # largest single activation in the trunk
    ff_chunk_size: int = 0
    template_attn_depth: int = 2
    dtype: Any = jnp.float32
    # weight residency/precision arm (INFERENCE-ONLY):
    #   "f32"  — fp32 master weights, the training/default arm;
    #   "int8" — per-channel symmetric post-training quantization of the
    #     trunk's dense/projection weights (ops/quant.py quantize_tree):
    #     int8 values + f32 per-output-channel scales, dequant fused into
    #     the matmul epilogue on the TPU kernel path
    #     (ops/quant_kernel.py) so no fp32 weight copy ever crosses HBM.
    #     The serving tier quantizes at engine build (keyed by config
    #     tag, serving/quant_residency.py); training entry points reject
    #     this value loudly (ops/quant.py reject_quant_training). Changes
    #     numerics: part of the serving config tag by repr construction.
    weight_dtype: str = "f32"

    def __post_init__(self):
        if self.reversible and self.remat:
            raise ValueError(
                "reversible=True and remat=True are mutually exclusive "
                "activation-memory strategies; pick one"
            )
        if self.cross_attn_mode not in ("flat", "aligned"):
            raise ValueError(
                f"cross_attn_mode must be 'flat' or 'aligned', "
                f"got {self.cross_attn_mode!r}"
            )
        t = self.attn_flash_qb_target
        if t is not None and (t <= 0 or t % 128):
            raise ValueError(
                f"attn_flash_qb_target must be a positive multiple of 128 "
                f"(TPU lane alignment), got {t}"
            )
        if self.remat_policy not in (None, "dots", "dots_no_batch"):
            raise ValueError(
                f"remat_policy must be None, 'dots', or 'dots_no_batch', "
                f"got {self.remat_policy!r}"
            )
        if self.trunk_schedule not in ("serial", "branch_parallel"):
            raise ValueError(
                f"trunk_schedule must be 'serial' or 'branch_parallel', "
                f"got {self.trunk_schedule!r}"
            )
        if self.weight_dtype not in ("f32", "int8"):
            raise ValueError(
                f"weight_dtype must be 'f32' or 'int8', "
                f"got {self.weight_dtype!r}"
            )
        if self.attn_gate and (
            self.sparse_self_attn is True
            or (isinstance(self.sparse_self_attn, tuple)
                and any(self.sparse_self_attn))
        ):
            raise ValueError(
                "attn_gate is not supported with sparse self-attention "
                "(the block-sparse path has no gate projection)"
            )

    @property
    def layer_sparse(self) -> Tuple[bool, ...]:
        v = self.sparse_self_attn
        return v if isinstance(v, tuple) else (bool(v),) * self.depth

    def sparse_config(self):
        from alphafold2_tpu.ops.sparse import SparseConfig

        return SparseConfig(
            block_size=self.sparse_block_size,
            num_random_blocks=self.sparse_num_random_blocks,
            num_local_blocks=self.sparse_num_local_blocks,
            num_global_blocks=self.sparse_num_global_blocks,
            layout_seed=self.sparse_layout_seed,
            max_seq_len=self.max_seq_len,
        )

    def self_attn_config(self) -> AttentionConfig:
        return AttentionConfig(
            dim=self.dim,
            heads=self.heads,
            dim_head=self.dim_head,
            dropout=self.attn_dropout,
            dtype=self.dtype,
            flash=self.attn_flash,
            batch_chunk=self.attn_batch_chunk,
            flash_tile_elems=self.attn_flash_tile_elems,
            flash_kv_block=self.attn_flash_kv_block,
            flash_qb_target=self.attn_flash_qb_target,
            flash_compute_dtype_logits=self.attn_flash_compute_dtype_logits,
            gate=self.attn_gate,
        )

    def cross_attn_config(self) -> AttentionConfig:
        return AttentionConfig(
            dim=self.dim,
            heads=self.heads,
            dim_head=self.dim_head,
            dropout=self.attn_dropout,
            compress_ratio=self.cross_attn_compress_ratio,
            dtype=self.dtype,
            flash=self.attn_flash,
            batch_chunk=self.attn_batch_chunk,
            flash_tile_elems=self.attn_flash_tile_elems,
            flash_kv_block=self.attn_flash_kv_block,
            flash_qb_target=self.attn_flash_qb_target,
            flash_compute_dtype_logits=self.attn_flash_compute_dtype_logits,
            gate=self.attn_gate,
        )
