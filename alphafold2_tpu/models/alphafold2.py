"""The Alphafold2 model: embeddings -> (template tower) -> dual-track trunk ->
distogram head.

Re-design of the reference model (reference alphafold2_pytorch/alphafold2.py:
328-545) as pure init/apply functions. The pair representation is the outer
sum of token embeddings plus an axial positional embedding; the MSA stream is
token + column-position + row-position embeddings (or a projection of
precomputed language-model embeddings); templates run through a pre-trunk
tower with attention along the template axis (TimeSformer-style,
reference alphafold2.py:479-524); the head symmetrizes the pair rep and
projects to distogram buckets.

Deliberate reference-bug fixes (documented divergences):
  * the `embedds` path crashes in the reference (`msa_shape` unbound,
    reference alphafold2.py:531) — here the embedds grid is a first-class
    (b, n, n, d) MSA-replacement stream;
  * templates without `templates_mask` crash in the reference (`t_mask`
    unbound, reference alphafold2.py:504) — here the mask is optional.
Reference quirks preserved for numerical parity:
  * the template tower's seq self-attention has NO residual
    (reference alphafold2.py:503);
  * `seq_pos` in a `(seq, seq_pos)` input pair is accepted and ignored (the
    reference unpacks it and never uses it, reference alphafold2.py:435-436).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from alphafold2_tpu.models.config import Alphafold2Config
from alphafold2_tpu.models.reversible import (
    reversible_trunk_apply,
    reversible_trunk_init,
)
from alphafold2_tpu.models.trunk import (
    prenorm_axial_apply,
    prenorm_axial_init,
    prenorm_ff_apply,
    prenorm_ff_init,
    sequential_trunk_apply,
    trunk_layer_init,
)
from alphafold2_tpu.ops.attention import attention_apply, attention_init
from alphafold2_tpu.ops.core import (
    embedding,
    embedding_init,
    layer_norm,
    layer_norm_init,
    linear,
    linear_init,
)


def _prenorm_attn_init(key, cfg: Alphafold2Config):
    return {
        "norm": layer_norm_init(cfg.dim),
        "attn": attention_init(key, cfg.self_attn_config()),
    }


def alphafold2_init(key, cfg: Alphafold2Config):
    """Initialize all model params (embeddings, template tower, trunk, head).

    sparse_self_attn composes with reversible=True: the reversible trunk
    segments its scan by runs of equal sparse flags (models/reversible.py),
    matching the reference's `sparse_self_attn=(True, False)*6` with
    `reversible=True` capability (reference alphafold2.py:349,407-411)."""
    keys = jax.random.split(key, 16)
    params = {
        # embeddings (reference alphafold2.py:351-368)
        "token_emb": embedding_init(keys[0], cfg.num_tokens, cfg.dim),
        "pos_emb": embedding_init(keys[1], cfg.max_seq_len, cfg.dim),
        "pos_emb_ax": embedding_init(keys[2], cfg.max_seq_len, cfg.dim),
        "msa_pos_emb": embedding_init(keys[3], cfg.max_seq_len, cfg.dim),
        "msa_num_pos_emb": embedding_init(keys[4], cfg.max_num_msa, cfg.dim),
        "template_emb": embedding_init(keys[5], cfg.num_buckets, cfg.dim),
        "template_pos_emb": embedding_init(keys[6], cfg.max_seq_len, cfg.dim),
        "template_pos_emb_ax": embedding_init(keys[7], cfg.max_seq_len, cfg.dim),
        "embedd_project": linear_init(keys[8], cfg.num_embedds, cfg.dim),
        # head (reference alphafold2.py:415-418)
        "head_norm": layer_norm_init(cfg.dim),
        "head_out": linear_init(keys[9], cfg.dim, cfg.num_buckets),
    }

    # template tower (reference alphafold2.py:375-384)
    tower = []
    tkey = keys[10]
    for _ in range(cfg.template_attn_depth):
        tkey, k1, k2, k3, k4 = jax.random.split(tkey, 5)
        tower.append(
            {
                "seq_attn": prenorm_axial_init(k1, cfg, cfg.self_attn_config()),
                "template_attn": prenorm_axial_init(k2, cfg, cfg.self_attn_config()),
                "joint_attn": _prenorm_attn_init(k3, cfg),
                "template_ff": prenorm_ff_init(k4, cfg),
            }
        )
    params["template_tower"] = tower

    # trunk (reference alphafold2.py:386-405); reversible layers are stacked
    # along a leading depth axis so the trunk runs as one scanned body
    if cfg.reversible:
        params["trunk"] = reversible_trunk_init(keys[11], cfg)
    else:
        lkey = keys[11]
        layers = []
        for _ in range(cfg.depth):
            lkey, k = jax.random.split(lkey)
            layers.append(trunk_layer_init(k, cfg, reversible=False))
        params["trunk"] = layers

    return params


def _template_tower_apply(params, cfg, x, x_mask, templates, templates_mask, rng):
    """Pre-trunk template tower (reference alphafold2.py:479-524).

    x: pair rep (b, n, n, d); templates: (b, T, n, n) distogram-bucket ints.
    """
    b, num_t, n, _ = templates.shape
    d = cfg.dim
    self_cfg = cfg.self_attn_config()

    # embed templates + axial positional embedding (reference :484-493)
    t = embedding(params["template_emb"], templates, dtype=cfg.dtype)
    n_range = jnp.arange(n)
    pos = (
        embedding(params["template_pos_emb"], n_range, dtype=cfg.dtype)[:, None, :]
        + embedding(params["template_pos_emb_ax"], n_range, dtype=cfg.dtype)[None, :, :]
    )
    t = (t + pos[None, None]).reshape(b * num_t, n, n, d)

    t_mask = (
        templates_mask.reshape(b * num_t, n, n) if templates_mask is not None else None
    )
    x_mask_flat = x_mask.reshape(b, n * n) if x_mask is not None else None

    for li, layer in enumerate(params["template_tower"]):
        lrng = jax.random.fold_in(rng, li) if rng is not None else None
        rngs = jax.random.split(lrng, 4) if lrng is not None else [None] * 4

        # seq pair-rep self-attn — reference quirk: NO residual (:503)
        x = prenorm_axial_apply(layer["seq_attn"], self_cfg, x, mask=x_mask, rng=rngs[0])
        # template self-attn, with residual (:504)
        t = prenorm_axial_apply(
            layer["template_attn"], self_cfg, t, mask=t_mask, rng=rngs[1]
        ) + t

        # attention along the template axis: per pair position, the length
        # (T+1) sequence [x_pos; t_1..t_T] self-attends (:509-522)
        x_tok = x.reshape(b * n * n, 1, d)
        t_tok = t.reshape(b, num_t, n * n, d).transpose(0, 2, 1, 3).reshape(
            b * n * n, num_t, d
        )
        y = jnp.concatenate([x_tok, t_tok], axis=1)

        y_mask = None
        if templates_mask is not None and x_mask is not None:
            tm = t_mask.reshape(b, num_t, n * n).transpose(0, 2, 1).reshape(
                b * n * n, num_t
            )
            xm = x_mask_flat.reshape(b * n * n, 1)
            y_mask = jnp.concatenate([xm, tm], axis=1)

        y = attention_apply(
            layer["joint_attn"]["attn"],
            self_cfg,
            layer_norm(layer["joint_attn"]["norm"], y),
            mask=y_mask,
            rng=rngs[2],
        ) + y

        x = y[:, 0].reshape(b, n, n, d)
        t = y[:, 1:].reshape(b, n * n, num_t, d).transpose(0, 2, 1, 3).reshape(
            b * num_t, n, n, d
        )

        t = prenorm_ff_apply(layer["template_ff"], cfg, t, rng=rngs[3]) + t

    return x


def alphafold2_apply(
    params,
    cfg: Alphafold2Config,
    seq,
    msa=None,
    *,
    mask=None,
    msa_mask=None,
    templates=None,
    templates_mask=None,
    embedds=None,
    seq_pos=None,  # accepted and ignored (reference alphafold2.py:435-436)
    rng=None,
    trunk_fn=None,  # override the trunk, e.g. the sequence-parallel trunk
    # (parallel/sp_trunk.py alphafold2_apply_sp); called as
    # trunk_fn(params["trunk"], cfg, x, m, x_mask, msa_mask, rng)
):
    """Forward pass.

    Args:
      seq: (b, n) int tokens.
      msa: (b, rows, cols) int tokens, or None.
      mask: (b, n) bool.
      msa_mask: (b, rows, cols) bool.
      templates: (b, T, n, n) — int distogram buckets, or FLOAT raw
        pairwise distances in Angstroms, which are binned internally with
        the library thresholds (completes the reference's declared TODO
        "allow the main network to take care of binning raw template
        distograms", reference README.md:158; binning matches
        geometry.bucketize_distances / utils.py:29 thresholds).
      templates_mask: (b, T, n, n) bool.
      embedds: (b, n, num_embedds) precomputed language-model embeddings,
        used as the MSA-replacement stream when msa is None.
      rng: dropout key (None = deterministic / eval).

    Returns: distogram logits (b, n, n, num_buckets).
    """
    del seq_pos
    x, m, x_mask, m_mask, rng_trunk = alphafold2_front(
        params, cfg, seq, msa,
        mask=mask, msa_mask=msa_mask, templates=templates,
        templates_mask=templates_mask, embedds=embedds, rng=rng,
    )

    # trunk (reference :528-535)
    if trunk_fn is not None:
        if cfg.reversible:
            # params["trunk"] is the depth-STACKED pytree when reversible
            # (reversible_trunk_init), not the layer list the hook's
            # contract documents — reject rather than hand over the wrong
            # structure
            raise ValueError(
                "trunk_fn overrides receive the sequential layer list; "
                "set reversible=False"
            )
        x, m = trunk_fn(params["trunk"], cfg, x, m, x_mask, m_mask, rng_trunk)
    elif cfg.reversible:
        x, m = reversible_trunk_apply(
            params["trunk"],
            cfg,
            x,
            m,
            x_mask=x_mask,
            msa_mask=m_mask,
            rng=rng_trunk,
        )
    else:
        x, m = sequential_trunk_apply(
            params["trunk"],
            cfg,
            x,
            m,
            x_mask=x_mask,
            msa_mask=m_mask,
            rng=rng_trunk,
        )

    return alphafold2_head(params, cfg, x)


def alphafold2_front(
    params,
    cfg: Alphafold2Config,
    seq,
    msa=None,
    *,
    mask=None,
    msa_mask=None,
    templates=None,
    templates_mask=None,
    embedds=None,
    rng=None,
):
    """Everything before the trunk: embeddings, MSA stream, template tower.

    Split out of `alphafold2_apply` so multi-execution drivers
    (training/segmented.py) can run front / trunk segments / head as
    separate device executions. Returns (x, m, x_mask, m_mask, rng_trunk):
    the pair grid, the MSA stream (or None), their masks, and the dropout
    key for the trunk (rng split mirrors the monolithic apply exactly).
    """
    b, n = seq.shape

    # pair representation: outer sum of token embeddings (reference :440-444)
    e = embedding(params["token_emb"], seq, dtype=cfg.dtype)
    x = e[:, :, None, :] + e[:, None, :, :]
    x_mask = (
        (mask[:, :, None] | mask[:, None, :]) if mask is not None else None
    )

    # axial positional embedding (reference :455-456)
    if n > cfg.max_seq_len:
        # out-of-range jnp.take fills NaN under jit (see MSA checks below)
        raise ValueError(
            f"sequence length {n} exceeds max_seq_len={cfg.max_seq_len}"
        )
    n_range = jnp.arange(n)
    pos = (
        embedding(params["pos_emb"], n_range, dtype=cfg.dtype)[:, None, :]
        + embedding(params["pos_emb_ax"], n_range, dtype=cfg.dtype)[None, :, :]
    )
    x = x + pos[None]

    # MSA stream (reference :460-472)
    m = None
    m_mask = msa_mask
    if msa is not None:
        rows, cols = msa.shape[1], msa.shape[2]
        # out-of-range jnp.take fills NaN under jit — without these checks an
        # oversized MSA silently poisons the whole forward
        if rows > cfg.max_num_msa:
            raise ValueError(
                f"msa has {rows} rows but the row-position table holds "
                f"max_num_msa={cfg.max_num_msa}; raise max_num_msa in the "
                f"config (reference constants.py MAX_NUM_MSA)"
            )
        if cols > cfg.max_seq_len:
            raise ValueError(
                f"msa has {cols} columns but the position table holds "
                f"max_seq_len={cfg.max_seq_len}"
            )
        m = embedding(params["token_emb"], msa, dtype=cfg.dtype)
        m = m + embedding(params["msa_pos_emb"], jnp.arange(cols), dtype=cfg.dtype)[None, None]
        m = m + embedding(params["msa_num_pos_emb"], jnp.arange(rows), dtype=cfg.dtype)[None, :, None, :]
    elif embedds is not None:
        p = linear(params["embedd_project"], embedds, dtype=cfg.dtype)
        m = p[:, :, None, :] + p[:, None, :, :]  # (b, n, n, d) grid stream
        if m_mask is None:
            m_mask = x_mask  # the grid stream's validity is the pair mask

    rng_tower, rng_trunk = (
        jax.random.split(rng) if rng is not None else (None, None)
    )

    # template tower (reference :479-524)
    if templates is not None:
        if jnp.issubdtype(jnp.asarray(templates).dtype, jnp.floating):
            # raw Angstrom distances -> bucket ints (reference README.md:158
            # TODO, completed): same thresholds as the distogram head
            import numpy as np

            from alphafold2_tpu.constants import DISTANCE_THRESHOLDS

            # one source of truth: the library threshold table itself at
            # the default bucket count (searchsorted over bins[:-1] then
            # matches geometry.bucketize_distances EXACTLY, whatever the
            # table's spacing); other bucket counts resample its range so
            # labels always fit the template_emb table
            table = np.asarray(DISTANCE_THRESHOLDS, np.float32)
            if cfg.num_buckets == len(table):
                bins = table
            else:
                bins = np.linspace(table[0], table[-1], cfg.num_buckets)
            templates = jnp.searchsorted(
                jnp.asarray(bins[:-1]), jnp.asarray(templates, jnp.float32)
            ).astype(jnp.int32)
        x = _template_tower_apply(
            params, cfg, x, x_mask, templates, templates_mask, rng_tower
        )
    return x, m, x_mask, m_mask, rng_trunk


def alphafold2_head(params, cfg: Alphafold2Config, x):
    """Distogram head: symmetrize + LayerNorm + project (reference :543-545)."""
    x = (x + jnp.swapaxes(x, 1, 2)) * 0.5
    x = layer_norm(params["head_norm"], x)
    return linear(params["head_out"], x, dtype=cfg.dtype)
