"""Torch -> JAX weight conversion for the reference Alphafold2.

Load a trained `alphafold2_pytorch.Alphafold2` module and map its weights
onto this framework's parameter pytrees — the migration path for users
switching from the reference (every module it builds,
reference alphafold2_pytorch/alphafold2.py:328-545 / reversible.py:304-313,
has a mapped twin here). Duck-typed attribute traversal: any object with
the reference's module structure works; torch itself is only touched via
`.detach().cpu().numpy()` on leaves.

The parity suite (tests/test_model_parity.py) uses exactly this converter
to compare our forward against the reference oracle at 2e-4 — the mapping
is verified, not aspirational. The ESM-1b embedder has its own converter
(models/embedder.py convert_esm_state_dict).
"""

from __future__ import annotations

import numpy as np


def t2n(t):
    return t.detach().cpu().numpy().astype(np.float32)


def convert_linear(torch_linear):
    """torch.nn.Linear (out, in) -> {'w': (in, out), 'b': (out,)}."""
    p = {"w": t2n(torch_linear.weight).T}
    if torch_linear.bias is not None:
        p["b"] = t2n(torch_linear.bias)
    return p


def convert_layernorm(torch_ln):
    return {"scale": t2n(torch_ln.weight), "bias": t2n(torch_ln.bias)}


def convert_attention(torch_attn):
    """Reference Attention module -> our attention params pytree."""
    p = {
        "to_q": convert_linear(torch_attn.to_q),
        "to_kv": convert_linear(torch_attn.to_kv),
        "to_out": convert_linear(torch_attn.to_out),
    }
    if torch_attn.compress_fn is not None:
        # torch Conv1d weight (out, in/groups, k) -> ours (k, in/groups, out)
        w = t2n(torch_attn.compress_fn.weight)
        p["compress"] = {
            "w": np.transpose(w, (2, 1, 0)),
            "b": t2n(torch_attn.compress_fn.bias),
        }
    return p


def convert_axial_attention(torch_axial):
    return {
        "attn_width": convert_attention(torch_axial.attn_width),
        "attn_height": convert_attention(torch_axial.attn_height),
    }


def convert_feed_forward(torch_ff):
    return {
        "proj_in": convert_linear(torch_ff.net[0]),
        "proj_out": convert_linear(torch_ff.net[3]),
    }


def convert_embedding(torch_emb):
    return {"table": t2n(torch_emb.weight)}


def _convert_prenorm_axial(m):
    return {"norm": convert_layernorm(m.norm), "attn": convert_axial_attention(m.fn)}


def _convert_prenorm_attn(m):
    return {"norm": convert_layernorm(m.norm), "attn": convert_attention(m.fn)}


def _convert_prenorm_cross(m):
    return {
        "norm": convert_layernorm(m.norm),
        "norm_context": convert_layernorm(m.norm_context),
        "attn": convert_attention(m.fn),
    }


def _convert_prenorm_ff(m):
    return {"norm": convert_layernorm(m.norm), "ff": convert_feed_forward(m.fn)}


def convert_reversible_trunk(rev_sequence):
    """Reference ReversibleSequence -> our per-layer params list (unstacked).

    Reference block layout (reversible.py:304-313): blocks alternate
    ReversibleSelfAttnBlock(f=seq axial attn, g=seq ff, j=msa axial attn,
    k=msa ff) and ReversibleCrossAttnBlock(f=seq cross, g=seq ff2,
    j=msa cross, k=msa ff2); each sub-fn is wrapped in Deterministic (.net).
    """
    blocks = list(rev_sequence.blocks)
    layers = []
    for self_blk, cross_blk in zip(*[iter(blocks)] * 2):
        layers.append(
            {
                "seq_attn": _convert_prenorm_axial(self_blk.f.net),
                "seq_ff": _convert_prenorm_ff(self_blk.g.net),
                "msa_attn": _convert_prenorm_axial(self_blk.j.net),
                "msa_ff": _convert_prenorm_ff(self_blk.k.net),
                "seq_cross": _convert_prenorm_cross(cross_blk.f.net),
                "seq_ff2": _convert_prenorm_ff(cross_blk.g.net),
                "msa_cross": _convert_prenorm_cross(cross_blk.j.net),
                "msa_ff2": _convert_prenorm_ff(cross_blk.k.net),
            }
        )
    return layers


def convert_alphafold2(model):
    """Reference Alphafold2 module -> our full params pytree (sequential)."""
    p = {
        "token_emb": convert_embedding(model.token_emb),
        "pos_emb": convert_embedding(model.pos_emb),
        "pos_emb_ax": convert_embedding(model.pos_emb_ax),
        "msa_pos_emb": convert_embedding(model.msa_pos_emb),
        "msa_num_pos_emb": convert_embedding(model.msa_num_pos_emb),
        "template_emb": convert_embedding(model.template_emb),
        "template_pos_emb": convert_embedding(model.template_pos_emb),
        "template_pos_emb_ax": convert_embedding(model.template_pos_emb_ax),
        "embedd_project": convert_linear(model.embedd_project),
        "head_norm": convert_layernorm(model.to_distogram_logits[0]),
        "head_out": convert_linear(model.to_distogram_logits[1]),
    }

    tower = []
    for seq_attn, tmpl_attn, joint_attn, ff in model.template_attn_net:
        tower.append(
            {
                "seq_attn": _convert_prenorm_axial(seq_attn),
                "template_attn": _convert_prenorm_axial(tmpl_attn),
                "joint_attn": _convert_prenorm_attn(joint_attn),
                "template_ff": _convert_prenorm_ff(ff),
            }
        )
    p["template_tower"] = tower

    if type(model.net).__name__ == "ReversibleSequence":
        p["trunk"] = convert_reversible_trunk(model.net)
        return p

    trunk = []
    blocks = list(model.net.blocks)
    for g1, g2 in zip(*[iter(blocks)] * 2):
        attn, ff, msa_attn = g1[0], g1[1], g1[2]
        cross, msa_ff, msa_cross = g2[0], g2[1], g2[2]
        trunk.append(
            {
                "seq_attn": _convert_prenorm_axial(attn),
                "seq_ff": _convert_prenorm_ff(ff),
                "msa_attn": _convert_prenorm_axial(msa_attn),
                "seq_cross": _convert_prenorm_cross(cross),
                "msa_ff": _convert_prenorm_ff(msa_ff),
                "msa_cross": _convert_prenorm_cross(msa_cross),
            }
        )
    p["trunk"] = trunk
    return p
