"""Protein language-model embedder (ESM-1b-compatible architecture).

The reference obtains per-residue embeddings by running Facebook's ESM-1b
(esm1b_t33_650M_UR50S) through torch.hub on a GPU and slicing representation
layer 33 (reference train_end2end.py:37-43,54-59); the embeddings then enter
the model through the `embedds` input (reference alphafold2.py:469-472, our
models/alphafold2.py embedds path). This module is the TPU-native embedder
for that contract:

  * the same architecture family as ESM-1b — pre-LN transformer encoder,
    learned positional embeddings, GELU MLP, final LayerNorm — expressed as
    pure init/apply over a param pytree, jit/pjit-ready;
  * `convert_esm_state_dict` maps a torch ESM-1b `state_dict()` (host-side
    numpy) onto the pytree, so the real 650M-param weights drop in when
    available — the architecture hyperparameters default to ESM-1b's
    (33 layers, 1280 dim, 20 heads); `convert_hf_esm_state_dict` accepts
    the same weights in HuggingFace `EsmModel` layout, and numerical
    parity of the whole path is pinned against transformers' independent
    torch implementation (tests/test_embedder.py), the strongest oracle
    available without the 30 GB hub download;
  * `esm_tokenize` converts our amino-acid vocabulary (constants.AA_ORDER)
    to the ESM alphabet with BOS/EOS framing, and `embed_sequences` strips
    the framing back off so the output aligns 1:1 with residues.

Embeddings feed `Alphafold2Config.num_embedds` = 1280 (constants.py,
reference constants.py:7).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from alphafold2_tpu.constants import AA_ORDER
from alphafold2_tpu.ops.core import (
    embedding,
    embedding_init,
    layer_norm as _layer_norm,
    layer_norm_init,
    linear,
    linear_init,
)

# ESM-1b LayerNorm runs at eps=1e-12 (fair-esm ESM1bLayerNorm, mirrored by
# HF EsmConfig.layer_norm_eps) — NOT our model-wide 1e-5 default. With the
# real 650M weights the wrong eps shifts representations by ~1e-3
# (measured against the transformers EsmModel oracle,
# tests/test_embedder.py).
_ESM_LN_EPS = 1e-12


def layer_norm(params, x):
    return _layer_norm(params, x, eps=_ESM_LN_EPS)

# the ESM alphabet (fair-esm constants): specials + amino acids in ESM order
ESM_TOKENS = (
    "<cls>", "<pad>", "<eos>", "<unk>",
    "L", "A", "G", "V", "S", "E", "R", "T", "I", "D", "P", "K",
    "Q", "N", "F", "Y", "M", "H", "W", "C", "X", "B", "U", "Z", "O",
    ".", "-", "<null_1>", "<mask>",
)
ESM_IDX = {t: i for i, t in enumerate(ESM_TOKENS)}
_CLS, _PAD, _EOS = ESM_IDX["<cls>"], ESM_IDX["<pad>"], ESM_IDX["<eos>"]
_MASK = ESM_IDX["<mask>"]

# our token id (0..19 = AA_ORDER, 20 = pad) -> ESM alphabet id
_OURS_TO_ESM = np.array(
    [ESM_IDX[aa] for aa in AA_ORDER] + [_PAD], dtype=np.int32
)


@dataclasses.dataclass(frozen=True)
class EmbedderConfig:
    """ESM-1b shape defaults (esm1b_t33_650M_UR50S)."""

    num_layers: int = 33
    dim: int = 1280
    heads: int = 20
    vocab: int = len(ESM_TOKENS)
    # max framed length (residues + cls/eos). fairseq position ids run up to
    # max_len + padding_idx, so the table holds max_len + padding_idx + 1
    # rows — (1026, 1280) for real ESM-1b, matching its state dict
    max_len: int = 1024
    # ESM "mask-dropout": real ESM-1b INFERENCE zeroes <mask>-token
    # embeddings and rescales every token embedding by
    # (1 - 0.15*0.8) / (1 - observed <mask> fraction) — a flat 0.88x when
    # no <mask> tokens are present. The reference's torch.hub ESM-1b
    # applies this (fair-esm esm1.py token_dropout; HF EsmEmbeddings
    # mirrors it), so faithfully reproducing the layer-33 representations
    # the reference feeds (train_end2end.py:54-59) requires it ON. Parity
    # with HF is pinned BOTH ways (tests/test_embedder.py).
    token_dropout: bool = True
    dtype: Any = jnp.float32

    @property
    def pos_table_rows(self) -> int:
        return self.max_len + _PAD + 1

    @property
    def head_dim(self) -> int:
        return self.dim // self.heads


def embedder_init(key, cfg: EmbedderConfig):
    keys = jax.random.split(key, 3 + cfg.num_layers)
    params = {
        "token_emb": embedding_init(keys[0], cfg.vocab, cfg.dim),
        "pos_emb": embedding_init(keys[1], cfg.pos_table_rows, cfg.dim),
        "pre_norm": layer_norm_init(cfg.dim),  # ESM-1b emb_layer_norm_before
        "final_norm": layer_norm_init(cfg.dim),
        "layers": [],
    }
    for li in range(cfg.num_layers):
        k = jax.random.split(keys[3 + li], 6)
        params["layers"].append(
            {
                "attn_norm": layer_norm_init(cfg.dim),
                "qkv": linear_init(k[0], cfg.dim, 3 * cfg.dim),
                "attn_out": linear_init(k[1], cfg.dim, cfg.dim),
                "ff_norm": layer_norm_init(cfg.dim),
                "ff_in": linear_init(k[2], cfg.dim, 4 * cfg.dim),
                "ff_out": linear_init(k[3], 4 * cfg.dim, cfg.dim),
            }
        )
    return params


def apply_token_dropout(h, tokens, mask):
    """ESM mask-dropout on token embeddings h (b, n, d), BEFORE positional
    embeddings are added (HF EsmEmbeddings order): zero <mask> positions,
    rescale every row by (1 - 0.15*0.8) / (1 - observed <mask> fraction)
    — a flat 0.88x when no <mask> tokens are present.

    Denominator = NON-PAD count, fair-esm semantics (esm1.py src_lengths
    = (~padding_mask).sum()) — the torch.hub ESM-1b the reference runs.
    NB: HF's EsmModel.forward drops the attention mask on the way into
    EsmEmbeddings, so for PADDED batches with <mask> present HF divides
    by the padded length instead; we follow fair-esm
    (tests/test_embedder.py pins it via padding invariance).
    """
    is_masked = tokens == _MASK
    h = jnp.where(is_masked[..., None], 0.0, h)
    mask_ratio_train = 0.15 * 0.8  # the ratio all ESM runs trained with
    src_lengths = jnp.maximum(  # guard the degenerate all-pad row
        jnp.sum(mask.astype(jnp.float32), axis=1), 1.0)
    ratio_obs = jnp.sum(is_masked.astype(jnp.float32), axis=1) / src_lengths
    return (h * ((1.0 - mask_ratio_train)
                 / (1.0 - ratio_obs))[:, None, None]).astype(h.dtype)


def embedder_apply(params, cfg: EmbedderConfig, tokens, mask=None):
    """Forward over ESM-alphabet tokens. tokens: (b, n) int; mask: (b, n).

    Returns (b, n, dim) final-layer representations (post final LayerNorm,
    the reference's `repr_layers=[33]` slice, train_end2end.py:55-58).
    """
    b, n = tokens.shape
    # fairseq position ids reach n + padding_idx; the table must cover that
    if n + _PAD >= cfg.pos_table_rows:
        raise ValueError(
            f"framed length {n} exceeds the positional table "
            f"(max_len={cfg.max_len}); jnp.take would clamp silently"
        )
    dtype = cfg.dtype
    if mask is None:
        mask = tokens != _PAD

    h = embedding(params["token_emb"], tokens, dtype=dtype)
    if cfg.token_dropout:
        h = apply_token_dropout(h, tokens, mask)
    # fairseq LearnedPositionalEmbedding semantics (what ESM-1b trained
    # with): position = cumulative count of non-pad tokens + padding_idx,
    # pads pinned at padding_idx — NOT a plain arange
    positions = jnp.cumsum(mask.astype(jnp.int32), axis=1) * mask + _PAD
    h = h + embedding(params["pos_emb"], positions, dtype=dtype)
    h = layer_norm(params["pre_norm"], h)  # ESM-1b emb_layer_norm_before

    bias = jnp.where(mask, 0.0, -1e30).astype(jnp.float32)[:, None, None, :]

    scale = cfg.head_dim ** -0.5
    for layer in params["layers"]:
        x = layer_norm(layer["attn_norm"], h)
        qkv = linear(layer["qkv"], x, dtype=dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(b, n, cfg.heads, cfg.head_dim)

        s = jnp.einsum("bqhd,bkhd->bhqk", heads(q), heads(k)).astype(jnp.float32)
        s = s * scale + bias
        p = jax.nn.softmax(s, axis=-1).astype(dtype)
        o = jnp.einsum("bhqk,bkhd->bqhd", p, heads(v)).reshape(b, n, cfg.dim)
        h = h + linear(layer["attn_out"], o, dtype=dtype)

        x = layer_norm(layer["ff_norm"], h)
        x = jax.nn.gelu(linear(layer["ff_in"], x, dtype=dtype), approximate=False)
        h = h + linear(layer["ff_out"], x, dtype=dtype)

    return layer_norm(params["final_norm"], h)


def esm_tokenize(our_tokens, our_mask=None):
    """Map our AA tokens (b, L) to ESM-alphabet tokens (b, L+2) with
    <cls>...<eos> framing, plus the framed mask.

    Like ESM's BatchConverter, <eos> sits immediately AFTER the last valid
    residue of each sequence (padding follows it), not at a fixed final
    slot — with contiguous-prefix masks the two agree only for full-length
    rows."""
    our_tokens = jnp.asarray(our_tokens)
    b, L = our_tokens.shape
    core = jnp.asarray(_OURS_TO_ESM)[our_tokens]
    if our_mask is None:
        our_mask = jnp.ones((b, L), bool)
    core = jnp.where(our_mask, core, _PAD)
    tokens = jnp.concatenate(
        [jnp.full((b, 1), _CLS, jnp.int32), core.astype(jnp.int32),
         jnp.full((b, 1), _PAD, jnp.int32)],
        axis=1,
    )
    mask = jnp.concatenate(
        [jnp.ones((b, 1), bool), our_mask, jnp.zeros((b, 1), bool)], axis=1
    )
    # <eos> right after the LAST valid residue of each row — computed from
    # the last True index, not the mask popcount, so a non-contiguous mask
    # can never overwrite a valid residue (it lands on a pad slot; the
    # popcount formula 1+sum(mask) would point inside the sequence)
    last_valid = jnp.max(
        jnp.where(our_mask, jnp.arange(L)[None, :], -1), axis=1
    )  # (b,), -1 for all-masked rows -> eos right after <cls>
    eos_pos = (last_valid + 2)[:, None]
    idx = jnp.arange(L + 2)[None, :]
    tokens = jnp.where(idx == eos_pos, _EOS, tokens)
    mask = mask | (idx == eos_pos)
    return tokens, mask


def embed_sequences(params, cfg: EmbedderConfig, our_tokens, our_mask=None):
    """Our-vocabulary sequences -> (b, L, dim) residue embeddings, aligned
    1:1 with input residues (BOS/EOS stripped — the reference's
    `[..., 1:-1]` slice at train_end2end.py:58)."""
    tokens, mask = esm_tokenize(our_tokens, our_mask)
    reps = embedder_apply(params, cfg, tokens, mask)
    return reps[:, 1:-1]


# --- torch weight conversion ------------------------------------------------

def convert_esm_state_dict(state_dict, cfg: EmbedderConfig):
    """Map a torch ESM-1b `state_dict()` (dict of numpy arrays / tensors)
    onto the embedder pytree.

    Key layout per fair-esm's ProteinBertModel: `embed_tokens.weight`,
    `embed_positions.weight`, `emb_layer_norm_after.{weight,bias}`, and per
    layer `layers.{i}.self_attn.{q,k,v,out}_proj.{weight,bias}`,
    `layers.{i}.self_attn_layer_norm.*`, `layers.{i}.fc1/fc2.*`,
    `layers.{i}.final_layer_norm.*`. Torch Linear stores (out, in); ours is
    (in, out) — transposed here.
    """
    sd = {k: np.asarray(v) for k, v in state_dict.items()}

    def lin(prefix):
        return {"w": sd[f"{prefix}.weight"].T.copy(), "b": sd[f"{prefix}.bias"].copy()}

    def norm(prefix):
        return {"scale": sd[f"{prefix}.weight"].copy(), "bias": sd[f"{prefix}.bias"].copy()}

    params = {
        "token_emb": {"table": sd["embed_tokens.weight"].copy()},
        "pos_emb": {"table": sd["embed_positions.weight"].copy()},
        "pre_norm": norm("emb_layer_norm_before"),
        "final_norm": norm("emb_layer_norm_after"),
        "layers": [],
    }
    for i in range(cfg.num_layers):
        p = f"layers.{i}"
        q = lin(f"{p}.self_attn.q_proj")
        k = lin(f"{p}.self_attn.k_proj")
        v = lin(f"{p}.self_attn.v_proj")
        params["layers"].append(
            {
                "attn_norm": norm(f"{p}.self_attn_layer_norm"),
                "qkv": {
                    "w": np.concatenate([q["w"], k["w"], v["w"]], axis=1),
                    "b": np.concatenate([q["b"], k["b"], v["b"]]),
                },
                "attn_out": lin(f"{p}.self_attn.out_proj"),
                "ff_norm": norm(f"{p}.final_layer_norm"),
                "ff_in": lin(f"{p}.fc1"),
                "ff_out": lin(f"{p}.fc2"),
            }
        )
    return jax.tree_util.tree_map(jnp.asarray, params)


# HuggingFace transformers EsmModel key -> fair-esm ProteinBertModel key.
# transformers ships an independently validated torch port of ESM
# (facebook/esm1b_t33_650M_UR50S is published in this format too), so
# accepting its state dicts both widens the real-weight loading path and
# gives the test suite a third-party numerical oracle for the
# architecture (tests/test_embedder.py).
_HF_STATIC = {
    "embeddings.word_embeddings.weight": "embed_tokens.weight",
    "embeddings.position_embeddings.weight": "embed_positions.weight",
    "embeddings.layer_norm.weight": "emb_layer_norm_before.weight",
    "embeddings.layer_norm.bias": "emb_layer_norm_before.bias",
    "encoder.emb_layer_norm_after.weight": "emb_layer_norm_after.weight",
    "encoder.emb_layer_norm_after.bias": "emb_layer_norm_after.bias",
}
_HF_LAYER = {
    "attention.self.query": "self_attn.q_proj",
    "attention.self.key": "self_attn.k_proj",
    "attention.self.value": "self_attn.v_proj",
    "attention.output.dense": "self_attn.out_proj",
    "attention.LayerNorm": "self_attn_layer_norm",
    "intermediate.dense": "fc1",
    "output.dense": "fc2",
    "LayerNorm": "final_layer_norm",
}


def convert_hf_esm_state_dict(state_dict, cfg: EmbedderConfig):
    """Map a HuggingFace `EsmModel` state dict (absolute-position / ESM-1b
    family, e.g. facebook/esm1b_t33_650M_UR50S in transformers format)
    onto the embedder pytree, via the fair-esm key layout."""
    sd = {}
    for key, val in state_dict.items():
        key = key.removeprefix("esm.")
        if key in _HF_STATIC:
            sd[_HF_STATIC[key]] = val
            continue
        if key.startswith("encoder.layer."):
            _, _, idx, rest = key.split(".", 3)
            stem, leaf = rest.rsplit(".", 1)
            if stem in _HF_LAYER:
                sd[f"layers.{idx}.{_HF_LAYER[stem]}.{leaf}"] = val
        # anything else (pooler, contact head, rotary buffers) is not part
        # of the representation path and is ignored

    # Validate the mapped layout BEFORE handing off: silently dropping
    # unmapped keys means an ESM-2/rotary-family checkpoint (no absolute
    # position table, no emb_layer_norm_before, different norm layout)
    # would fail later with an opaque KeyError deep in
    # convert_esm_state_dict. Name the unsupported layout instead.
    missing = [k for k in
               ("embed_tokens.weight", "embed_positions.weight",
                "emb_layer_norm_before.weight", "emb_layer_norm_after.weight")
               if k not in sd]
    missing += [f"layers.{i}.self_attn.q_proj.weight"
                for i in range(cfg.num_layers)
                if f"layers.{i}.self_attn.q_proj.weight" not in sd]
    if missing:
        raise ValueError(
            "state dict does not look like an absolute-position ESM-1b "
            f"family EsmModel (missing after mapping: {missing[:4]}"
            f"{'...' if len(missing) > 4 else ''}). ESM-2/rotary "
            "checkpoints (no position table, no emb_layer_norm_before) "
            "are not supported by this converter; check cfg.num_layers "
            "matches the checkpoint depth."
        )
    # a checkpoint DEEPER than cfg.num_layers would otherwise truncate
    # silently — wrong representations with no error
    extra = f"layers.{cfg.num_layers}.self_attn.q_proj.weight"
    if extra in sd:
        raise ValueError(
            f"checkpoint has more encoder layers than cfg.num_layers="
            f"{cfg.num_layers} (found {extra}); refusing to silently "
            "truncate — set cfg.num_layers to the checkpoint depth"
        )
    return convert_esm_state_dict(sd, cfg)
