"""SE(3)-equivariant structure refiner.

Functional replacement for the reference's *external* SE3Transformer
dependency (imported at reference alphafold2_pytorch/alphafold2.py:13,
instantiated in train_end2end.py:86-94, invoked at train_end2end.py:168-169
as `refiner(atom_tokens, proto_sidechain, mask) -> refined coords`;
declared as deps at setup.py:19,23).

Rather than porting the irreducible-representation (spherical-harmonic)
machinery of SE3-Transformer — which maps poorly onto the MXU (small tensor
products, gather-heavy) — this module uses an E(3)-equivariant message
passing network in the style of EGNN (Satorras et al., 2021): node features
are invariant (built from atom tokens and pairwise distances only), and
coordinate updates are linear combinations of difference vectors. That
gives exact rotation/translation equivariance with nothing but large dense
einsums, which is the TPU-native answer to the same functional contract:

  h_ij   = MLP(h_i, h_j, |x_i - x_j|^2)          # invariant messages
  a_ij   = sigmoid(w . h_ij)                     # attention gate
  x_i   <- x_i + mean_j a_ij * (x_i - x_j)/(|.|+1) * phi_x(h_ij)
  h_i   <- h_i + MLP(h_i, sum_j a_ij h_ij)

All pair tensors are dense (atoms, atoms) with a boolean mask — static
shapes, fully fusable by XLA. Cost is O(A^2 * msg_dim) per layer; at the
north-star crop (384 residues x 14 atoms = 5376 atoms) this fits
comfortably in HBM in bfloat16.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from alphafold2_tpu.ops.core import embedding, embedding_init, layer_norm, layer_norm_init, linear, linear_init


@dataclasses.dataclass(frozen=True)
class RefinerConfig:
    """Shape/capability config (mirrors the kwargs the reference passes to
    SE3Transformer at train_end2end.py:86-94: num_tokens=10 atom types,
    dim=64, depth=2)."""

    num_tokens: int = 10
    dim: int = 64
    depth: int = 2
    msg_dim: int = 64
    dtype: Any = jnp.float32
    # scale on the per-layer coordinate delta; final coord head is
    # zero-initialized so an untrained refiner is the identity on coords.
    coord_scale: float = 1.0
    # process query atoms in blocks of this size under jax.checkpoint
    # (0 = off): bounds the (A, A, msg_dim) pair-message tensor, which at
    # the north-star crop (5376 atoms) is 3.4 GB per copy and the training
    # backward holds several
    atom_chunk: int = 0


def _mlp_init(key, d_in, d_hidden, d_out):
    k1, k2 = jax.random.split(key)
    return {"l1": linear_init(k1, d_in, d_hidden), "l2": linear_init(k2, d_hidden, d_out)}


def _mlp(params, x, dtype):
    h = jax.nn.silu(linear(params["l1"], x, dtype=dtype))
    return linear(params["l2"], h, dtype=dtype)


def refiner_init(key, cfg: RefinerConfig):
    keys = jax.random.split(key, 1 + cfg.depth)
    params = {
        "token_emb": embedding_init(keys[0], cfg.num_tokens, cfg.dim),
        "out_norm": layer_norm_init(cfg.dim),
        "layers": [],
    }
    for li in range(cfg.depth):
        k = jax.random.split(keys[1 + li], 5)
        layer = {
            "edge_mlp": _mlp_init(k[0], 2 * cfg.dim + 1, cfg.msg_dim, cfg.msg_dim),
            "att": linear_init(k[1], cfg.msg_dim, 1),
            "coord_mlp": _mlp_init(k[2], cfg.msg_dim, cfg.msg_dim, 1),
            "node_mlp": _mlp_init(k[3], cfg.dim + cfg.msg_dim, cfg.dim, cfg.dim),
            "norm": layer_norm_init(cfg.dim),
        }
        # zero the final coord projection: identity coords at init
        layer["coord_mlp"]["l2"]["w"] = jnp.zeros_like(layer["coord_mlp"]["l2"]["w"])
        layer["coord_mlp"]["l2"]["b"] = jnp.zeros_like(layer["coord_mlp"]["l2"]["b"])
        params["layers"].append(layer)
    return params


def refiner_apply(params, cfg: RefinerConfig, tokens, coords, mask=None):
    """Refine an atom point cloud.

    Args:
      tokens: (b, A) int atom-type ids (the reference's `atom_tokens`,
        train_end2end.py:143-146).
      coords: (b, A, 3) float coordinates (the proto sidechain cloud,
        train_end2end.py:163-169).
      mask:   (b, A) bool atom presence; masked atoms neither send messages
        nor move.

    Returns: (refined_coords (b, A, 3), node_features (b, A, dim)).
    """
    b, num_atoms = tokens.shape
    dtype = cfg.dtype
    coords = coords.astype(jnp.float32)
    if mask is None:
        mask = jnp.ones((b, num_atoms), bool)

    # pair mask excludes self-edges and masked endpoints
    eye = jnp.eye(num_atoms, dtype=bool)[None]
    pair_mask = (mask[:, :, None] & mask[:, None, :]) & ~eye  # (b, A, A)
    denom = jnp.maximum(jnp.sum(pair_mask, axis=-1, keepdims=True), 1).astype(jnp.float32)

    h = embedding(params["token_emb"], tokens, dtype=dtype)  # (b, A, d)

    def message_pass(layer, hq_pre, hk_pre, coords_q, coords_all, pair_mask_q, w_sq, b1):
        """Messages from all atoms to a block of query atoms.

        hq_pre/coords_q/pair_mask_q: (b, qb, ...) query-block slices;
        hk_pre/coords_all: (b, A, ...) full key-side tensors. Returns the
        block's (delta (b, qb, 3), weighted message agg (b, qb, msg)).
        """
        diff = coords_q[:, :, None, :] - coords_all[:, None, :, :]  # (b, qb, A, 3)
        sqdist = jnp.sum(jnp.square(diff), axis=-1, keepdims=True)

        pre = (
            hq_pre[:, :, None, :]
            + hk_pre[:, None, :, :]
            + sqdist.astype(dtype) * w_sq
            + b1
        )
        m = linear(layer["edge_mlp"]["l2"], jax.nn.silu(pre), dtype=dtype)
        gate = jax.nn.sigmoid(linear(layer["att"], m, dtype=dtype))  # (b, qb, A, 1)
        gate = jnp.where(pair_mask_q[..., None], gate, 0.0)

        # equivariant coordinate update along normalized difference vectors
        coef = _mlp(layer["coord_mlp"], m, dtype).astype(jnp.float32)
        # clamp before sqrt: coincident atoms (the sidechain proto cloud
        # parks every non-backbone slot at the SAME point) and the diagonal
        # have sqdist == 0, where sqrt's vjp is inf and 0-gates cannot stop
        # it (0 * inf = nan); max() routes the gradient to the eps branch
        norm = jnp.sqrt(jnp.maximum(sqdist, 1e-12))
        direction = jnp.where(pair_mask_q[..., None], diff, 0.0) / (norm + 1.0)
        delta = jnp.sum(gate.astype(jnp.float32) * coef * direction, axis=2)
        agg = jnp.sum(gate * m, axis=2)  # (b, qb, msg)
        return delta, agg

    chunk = cfg.atom_chunk
    for layer in params["layers"]:
        # The edge MLP's first layer is linear over concat(h_i, h_j, |.|^2),
        # which is separable: project h once per *node* and broadcast-add,
        # so the largest pair tensor is (b, A, A, msg) rather than
        # (b, A, A, 2*dim+1) — at 5376 atoms that halves peak pair memory.
        d = h.shape[-1]
        w1 = layer["edge_mlp"]["l1"]["w"].astype(dtype)
        b1 = layer["edge_mlp"]["l1"]["b"].astype(dtype)
        hd = h.astype(dtype)
        hq_pre = hd @ w1[:d]  # (b, A, msg)
        hk_pre = hd @ w1[d : 2 * d]
        w_sq = w1[2 * d]

        if not chunk or num_atoms <= chunk:
            delta, agg = message_pass(
                layer, hq_pre, hk_pre, coords, coords, pair_mask, w_sq, b1
            )
        else:
            # map query-atom blocks under checkpoint: the (qb, A, msg) pair
            # tensor is the only live block, recomputed in backward
            pad = (-num_atoms) % chunk
            nq = (num_atoms + pad) // chunk

            def pad_q(t, fill=0):
                if not pad:
                    return t
                widths = [(0, 0), (0, pad)] + [(0, 0)] * (t.ndim - 2)
                return jnp.pad(t, widths, constant_values=fill)

            def to_blocks(t):
                return jnp.moveaxis(
                    t.reshape((t.shape[0], nq, chunk) + t.shape[2:]), 1, 0
                )

            blocks = (
                to_blocks(pad_q(hq_pre)),
                to_blocks(pad_q(coords)),
                to_blocks(pad_q(pair_mask, fill=False)),
            )

            def body(args):
                hq_b, cq_b, pm_b = args
                return message_pass(
                    layer, hq_b, hk_pre, cq_b, coords, pm_b, w_sq, b1
                )

            delta_b, agg_b = jax.lax.map(jax.checkpoint(body), blocks)
            delta = jnp.moveaxis(delta_b, 0, 1).reshape(b, nq * chunk, 3)[
                :, :num_atoms
            ]
            agg = jnp.moveaxis(agg_b, 0, 1).reshape(b, nq * chunk, -1)[
                :, :num_atoms
            ]

        delta = delta / denom
        coords = coords + cfg.coord_scale * jnp.where(mask[..., None], delta, 0.0)

        # invariant feature update
        agg = agg / denom.astype(agg.dtype)
        upd = _mlp(layer["node_mlp"], jnp.concatenate([h, agg], axis=-1), dtype)
        h = layer_norm(layer["norm"], h + upd)

    return coords, h
