"""Library-wide constants.

Parity: reference `alphafold2_pytorch/constants.py:5-8`. The reference also
pins a global torch DEVICE (`constants.py:12-13`); JAX needs no such global —
device placement is handled by jit/pjit and shardings.
"""

import numpy as np

# maximum number of rows of a multiple sequence alignment the row-position
# embedding table supports
MAX_NUM_MSA = 20

# 20 standard amino acids + 1 pad/unknown token
NUM_AMINO_ACIDS = 21

# width of precomputed language-model residue embeddings (ESM-1b final layer)
NUM_EMBEDDS_TR = 1280

# number of distance buckets of the distogram head (AlphaFold1-style)
DISTOGRAM_BUCKETS = 37

# distogram bucket boundaries in Angstroms (reference utils.py:29)
DISTANCE_THRESHOLDS = np.linspace(2.0, 20.0, DISTOGRAM_BUCKETS)

# number of atom slots per residue in the dense atom representation
# (sidechainnet layout: N, CA, C, O, then up to 10 side-chain heavy atoms)
NUM_COORDS_PER_RES = 14

# padding value used in dense atom clouds
GLOBAL_PAD_CHAR = 0

# carbonyl-group build constants used when placing the backbone oxygen
# (reference utils.py:20-21 fallback values)
BOND_LEN_C_O = 1.229
BOND_ANG_CA_C_O = 2.0944

# --- amino-acid vocabulary -------------------------------------------------
#
# Our own, explicitly defined vocabulary (the reference defers to
# sidechainnet's ProteinVocabulary, reference utils.py:11-16). Index 20 is the
# pad/unknown token. Heavy-atom counts include the 4 backbone atoms
# (N, CA, C, O).

AA_ORDER = "ACDEFGHIKLMNPQRSTVWY"  # alphabetical one-letter codes, ids 0..19
PAD_TOKEN_ID = 20

# total heavy atoms per residue (backbone 4 + side chain)
AA_NUM_HEAVY_ATOMS = {
    "A": 5,   # Ala
    "C": 6,   # Cys
    "D": 8,   # Asp
    "E": 9,   # Glu
    "F": 11,  # Phe
    "G": 4,   # Gly
    "H": 10,  # His
    "I": 8,   # Ile
    "K": 9,   # Lys
    "L": 8,   # Leu
    "M": 8,   # Met
    "N": 8,   # Asn
    "P": 7,   # Pro
    "Q": 9,   # Gln
    "R": 11,  # Arg
    "S": 6,   # Ser
    "T": 7,   # Thr
    "V": 7,   # Val
    "W": 14,  # Trp
    "Y": 12,  # Tyr
}

# atom-count lookup table indexed by token id; pad rows get 0 atoms
ATOMS_PER_TOKEN = np.array(
    [AA_NUM_HEAVY_ATOMS[aa] for aa in AA_ORDER] + [0], dtype=np.int32
)


def aa_to_tokens(seq: str, strict: bool = False) -> np.ndarray:
    """Encode a one-letter amino-acid string into integer tokens.

    By default unknown characters map to PAD_TOKEN_ID — the lenient
    behavior alignment parsing relies on (gaps and a3m '-' become pad).
    With ``strict=True`` any character outside the 20-residue vocabulary
    raises ValueError instead: request-facing boundaries (predict.py,
    serving.engine) must fail garbage input fast rather than silently
    predicting a structure for padding.
    """
    lookup = {aa: i for i, aa in enumerate(AA_ORDER)}
    if strict:
        bad = sorted({c for c in seq if c.upper() not in lookup})
        if bad:
            raise ValueError(
                f"invalid residue code(s) {''.join(bad)!r} in sequence "
                f"(valid one-letter codes: {AA_ORDER})"
            )
        if not seq:
            raise ValueError("empty sequence")
    return np.array([lookup.get(c.upper(), PAD_TOKEN_ID) for c in seq], dtype=np.int32)


def tokens_to_aa(tokens) -> str:
    """Decode integer tokens into a one-letter amino-acid string."""
    out = []
    for t in np.asarray(tokens).reshape(-1):
        out.append(AA_ORDER[int(t)] if 0 <= int(t) < len(AA_ORDER) else "X")
    return "".join(out)
