"""Version-compat shim: every version-dependent JAX name resolves HERE, once.

The seed's tier-1 suite went red on exactly the failure mode this module
exists to prevent: `jax.experimental.pallas.tpu.CompilerParams` (JAX >=
0.6) vs `TPUCompilerParams` (<= 0.5), `jax.shard_map(check_vma=...)` vs
`jax.experimental.shard_map.shard_map(check_rep=...)`, and
`jax.typeof`/`ShapeDtypeStruct(vma=...)` — all renamed between the JAX the
code was written against and the JAX in the image, each one crashing at
import or trace time after chip time was already scheduled. FastFold
(arxiv 2203.00854) and ScaleFold (arxiv 2404.11068) both make the point
that AlphaFold-scale iterations are too expensive to burn on avoidable
crashes; API drift is the most avoidable of all.

Contract, enforced statically by `alphafold2_tpu.analysis` (the `compat`
pass): no module outside this file touches `jax.experimental.*` or any
symbol in the drift table (analysis/drift.py). When JAX renames something,
the resolution moves here, the drift table gains a row, and every call
site keeps working on both sides of the rename.

Import idiom:

    from alphafold2_tpu import compat
    from alphafold2_tpu.compat import pallas as pl, pallas_tpu as pltpu

    compat.CompilerParams(dimension_semantics=...)
    compat.shard_map(f, mesh=mesh, in_specs=..., out_specs=..., check_vma=False)
    compat.out_struct(shape, dtype, q, k, v)   # vma-aware ShapeDtypeStruct
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax

__all__ = [
    "JAX_VERSION",
    "CompilerParams",
    "create_hybrid_device_mesh",
    "out_struct",
    "pallas",
    "pallas_tpu",
    "pcast",
    "shard_map",
    "typeof_vma",
]


def _version_tuple(v: str) -> tuple:
    parts = []
    for p in v.split(".")[:3]:
        digits = "".join(ch for ch in p if ch.isdigit())
        parts.append(int(digits) if digits else 0)
    return tuple(parts)


JAX_VERSION: tuple = _version_tuple(jax.__version__)

# --- pallas ----------------------------------------------------------------
# The pallas modules themselves live under jax.experimental on every JAX
# this repo supports; re-exported so kernel files never spell the
# experimental path (the compat linter forbids it outside this module).
# Resolved LAZILY (PEP 562 module __getattr__): most consumers of this
# module (parallel/mesh, sequence, pipeline, sp_trunk) only want
# shard_map/pcast, and the eager Pallas import costs ~0.26 s on top of
# jax's own import on every process start.
#
# `CompilerParams` (lazy too, it needs pallas_tpu): JAX >= 0.6 renamed
# TPUCompilerParams -> CompilerParams (drift table row
# `pltpu.CompilerParams`). Same kwargs (dimension_semantics, ...).


def __getattr__(name: str):
    if name == "pallas":
        from jax.experimental import pallas

        globals()["pallas"] = pallas
        return pallas
    if name == "pallas_tpu":
        from jax.experimental.pallas import tpu as pallas_tpu

        globals()["pallas_tpu"] = pallas_tpu
        return pallas_tpu
    if name == "CompilerParams":
        ptpu = __getattr__("pallas_tpu")
        cp = getattr(ptpu, "CompilerParams", None)
        if cp is None:  # JAX <= 0.5 (e.g. 0.4.37): only the old spelling
            cp = ptpu.TPUCompilerParams
        globals()["CompilerParams"] = cp
        return cp
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


# --- shard_map -------------------------------------------------------------
# JAX >= 0.6: jax.shard_map(..., check_vma=...). Older: the experimental
# module with the kwarg spelled check_rep. Semantics are the same knob
# (disable the replication/varying-across-mesh-axes checker).
_NEW_SHARD_MAP = hasattr(jax, "shard_map")
if not _NEW_SHARD_MAP:
    from jax.experimental.shard_map import shard_map as _old_shard_map


def shard_map(f=None, *, mesh, in_specs, out_specs, check_vma: Optional[bool] = None):
    """`jax.shard_map` across JAX versions; `check_vma` maps to the era's
    checker kwarg (`check_rep` before the rename). Usable directly or as a
    decorator factory (``f=None``), matching both eras' calling styles."""
    if f is None:
        return functools.partial(
            shard_map, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    kwargs: dict = {}
    if check_vma is not None:
        kwargs["check_vma" if _NEW_SHARD_MAP else "check_rep"] = check_vma
    impl = jax.shard_map if _NEW_SHARD_MAP else _old_shard_map
    return impl(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)


# --- vma-aware ShapeDtypeStruct -------------------------------------------
# JAX >= 0.7 tracks a `vma` (varying-across-mesh-axes) set on abstract
# values and requires pallas_call out_shapes under shard_map to declare
# theirs. Older JAX has neither jax.typeof nor the vma kwarg — there the
# plain struct is exactly right.
_HAS_VMA = hasattr(jax, "typeof") and "vma" in getattr(
    getattr(jax.ShapeDtypeStruct.__init__, "__code__", None), "co_varnames", ()
)


def typeof_vma(x: Any) -> frozenset:
    """The value's varying-across-mesh-axes set (empty set pre-vma JAX)."""
    if _HAS_VMA:
        return frozenset(jax.typeof(x).vma)
    return frozenset()


def out_struct(shape, dtype, *operands) -> jax.ShapeDtypeStruct:
    """ShapeDtypeStruct whose `vma` is the union of the operands' — required
    for pallas_call under shard_map with vma checking (e.g. ring-attention
    hops) on new JAX; collapses to a plain struct on old JAX."""
    if _HAS_VMA:
        vma = frozenset().union(*(typeof_vma(o) for o in operands))
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def pcast(x, axis_names, *, to: str = "varying"):
    """`jax.lax.pcast` (vma-era JAX): mark a value varying/invariant over
    mesh axes so shard_map carry types line up after collectives. Pre-vma
    JAX tracks no such set — the identity is the exact semantic there."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axis_names, to=to)
    return x


# --- device mesh helpers ---------------------------------------------------

def create_hybrid_device_mesh(**kwargs):
    """jax.experimental.mesh_utils.create_hybrid_device_mesh, resolved here
    so parallel/mesh.py stays free of experimental imports."""
    from jax.experimental import mesh_utils

    return mesh_utils.create_hybrid_device_mesh(**kwargs)
