"""Version-compat shim: every version-dependent JAX name resolves HERE, once.

The seed's tier-1 suite went red on exactly the failure mode this module
exists to prevent: `jax.experimental.pallas.tpu.CompilerParams` (JAX >=
0.6) vs `TPUCompilerParams` (<= 0.5), `jax.shard_map(check_vma=...)` vs
`jax.experimental.shard_map.shard_map(check_rep=...)`, and
`jax.typeof`/`ShapeDtypeStruct(vma=...)` — all renamed between the JAX the
code was written against and the JAX in the image, each one crashing at
import or trace time after chip time was already scheduled. FastFold
(arxiv 2203.00854) and ScaleFold (arxiv 2404.11068) both make the point
that AlphaFold-scale iterations are too expensive to burn on avoidable
crashes; API drift is the most avoidable of all.

Contract, enforced statically by `alphafold2_tpu.analysis` (the `compat`
pass): no module outside this file touches `jax.experimental.*` or any
symbol in the drift table (analysis/drift.py). When JAX renames something,
the resolution moves here, the drift table gains a row, and every call
site keeps working on both sides of the rename.

Import idiom:

    from alphafold2_tpu import compat
    from alphafold2_tpu.compat import pallas as pl, pallas_tpu as pltpu

    compat.CompilerParams(dimension_semantics=...)
    compat.shard_map(f, mesh=mesh, in_specs=..., out_specs=..., check_vma=False)
    compat.out_struct(shape, dtype, q, k, v)   # vma-aware ShapeDtypeStruct
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax

__all__ = [
    "JAX_VERSION",
    "CompilerParams",
    "backend_initialized",
    "broadcast_one_to_all",
    "enable_cpu_collectives",
    "create_hybrid_device_mesh",
    "make_array_from_process_local_data",
    "make_global_array_from_host",
    "out_struct",
    "pallas",
    "pallas_tpu",
    "pcast",
    "process_allgather",
    "shard_map",
    "sync_global_devices",
    "typeof_vma",
]


def _version_tuple(v: str) -> tuple:
    parts = []
    for p in v.split(".")[:3]:
        digits = "".join(ch for ch in p if ch.isdigit())
        parts.append(int(digits) if digits else 0)
    return tuple(parts)


JAX_VERSION: tuple = _version_tuple(jax.__version__)

# --- pallas ----------------------------------------------------------------
# The pallas modules themselves live under jax.experimental on every JAX
# this repo supports; re-exported so kernel files never spell the
# experimental path (the compat linter forbids it outside this module).
# Resolved LAZILY (PEP 562 module __getattr__): most consumers of this
# module (parallel/mesh, sequence, pipeline, sp_trunk) only want
# shard_map/pcast, and the eager Pallas import costs ~0.26 s on top of
# jax's own import on every process start.
#
# `CompilerParams` (lazy too, it needs pallas_tpu): JAX >= 0.6 renamed
# TPUCompilerParams -> CompilerParams (drift table row
# `pltpu.CompilerParams`). Same kwargs (dimension_semantics, ...).


def __getattr__(name: str):
    if name == "pallas":
        from jax.experimental import pallas

        globals()["pallas"] = pallas
        return pallas
    if name == "pallas_tpu":
        from jax.experimental.pallas import tpu as pallas_tpu

        globals()["pallas_tpu"] = pallas_tpu
        return pallas_tpu
    if name == "CompilerParams":
        ptpu = __getattr__("pallas_tpu")
        cp = getattr(ptpu, "CompilerParams", None)
        if cp is None:  # JAX <= 0.5 (e.g. 0.4.37): only the old spelling
            cp = ptpu.TPUCompilerParams
        globals()["CompilerParams"] = cp
        return cp
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


# --- shard_map -------------------------------------------------------------
# JAX >= 0.6: jax.shard_map(..., check_vma=...). Older: the experimental
# module with the kwarg spelled check_rep. Semantics are the same knob
# (disable the replication/varying-across-mesh-axes checker).
_NEW_SHARD_MAP = hasattr(jax, "shard_map")
if not _NEW_SHARD_MAP:
    from jax.experimental.shard_map import shard_map as _old_shard_map


def shard_map(f=None, *, mesh, in_specs, out_specs, check_vma: Optional[bool] = None):
    """`jax.shard_map` across JAX versions; `check_vma` maps to the era's
    checker kwarg (`check_rep` before the rename). Usable directly or as a
    decorator factory (``f=None``), matching both eras' calling styles."""
    if f is None:
        return functools.partial(
            shard_map, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    kwargs: dict = {}
    if check_vma is not None:
        kwargs["check_vma" if _NEW_SHARD_MAP else "check_rep"] = check_vma
    impl = jax.shard_map if _NEW_SHARD_MAP else _old_shard_map
    return impl(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)


# --- vma-aware ShapeDtypeStruct -------------------------------------------
# JAX >= 0.7 tracks a `vma` (varying-across-mesh-axes) set on abstract
# values and requires pallas_call out_shapes under shard_map to declare
# theirs. Older JAX has neither jax.typeof nor the vma kwarg — there the
# plain struct is exactly right.
_HAS_VMA = hasattr(jax, "typeof") and "vma" in getattr(
    getattr(jax.ShapeDtypeStruct.__init__, "__code__", None), "co_varnames", ()
)


def typeof_vma(x: Any) -> frozenset:
    """The value's varying-across-mesh-axes set (empty set pre-vma JAX)."""
    if _HAS_VMA:
        return frozenset(jax.typeof(x).vma)
    return frozenset()


def out_struct(shape, dtype, *operands) -> jax.ShapeDtypeStruct:
    """ShapeDtypeStruct whose `vma` is the union of the operands' — required
    for pallas_call under shard_map with vma checking (e.g. ring-attention
    hops) on new JAX; collapses to a plain struct on old JAX."""
    if _HAS_VMA:
        vma = frozenset().union(*(typeof_vma(o) for o in operands))
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def pcast(x, axis_names, *, to: str = "varying"):
    """`jax.lax.pcast` (vma-era JAX): mark a value varying/invariant over
    mesh axes so shard_map carry types line up after collectives. Pre-vma
    JAX tracks no such set — the identity is the exact semantic there."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axis_names, to=to)
    return x


# --- multi-host runtime ----------------------------------------------------
# The multihost utilities live under jax.experimental on every JAX this
# repo supports; resolved here so parallel/distributed.py and
# training/checkpoint.py stay free of experimental imports (compat-lint
# contract). `make_array_from_process_local_data` moved to the jax
# namespace in 0.4.31 — older trees fall back to per-device assembly
# from process-index slices (the "process-index slicing" route).


def enable_cpu_collectives() -> bool:
    """Select a cross-process collectives implementation for the CPU
    backend (Gloo). Without one, a multi-process CPU runtime enumerates
    the pod's devices but every cross-process computation dies with
    "Multiprocess computations aren't implemented on the CPU backend" —
    the 2-process test matrix (and any CPU-pod rehearsal) needs this set
    BEFORE backend init. Returns False when this jaxlib has no such
    option (TPU-only builds, future renames); harmless then, since only
    CPU multi-process paths need it."""
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        return True
    except Exception:
        return False


def backend_initialized() -> bool:
    """True once any XLA backend has been created in this process — the
    point past which `jax.distributed.initialize` is too late (the
    backend already enumerated only-local devices). Resolution is
    version-tolerant: the public predicate when present, else the
    backend cache xla_bridge maintains on every supported JAX."""
    try:
        from jax.lib import xla_bridge as xb
    except Exception:  # pragma: no cover - layout drift
        return False
    fn = getattr(xb, "backends_are_initialized", None)
    if fn is not None:
        try:
            return bool(fn())
        except Exception:  # pragma: no cover
            pass
    return bool(getattr(xb, "_backends", None))


def sync_global_devices(name: str) -> None:
    """Cross-process barrier (multihost_utils.sync_global_devices): every
    process blocks until all reach the same named point. No-op with one
    process — callers need no guard."""
    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def broadcast_one_to_all(x, is_source: Optional[bool] = None):
    """multihost_utils.broadcast_one_to_all: process 0's value on every
    process (identity single-process)."""
    if jax.process_count() <= 1:
        return x
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(x, is_source=is_source)


def process_allgather(x, *, tiled: bool = True):
    """multihost_utils.process_allgather: the GLOBAL value of a (possibly
    cross-process-sharded) array, materialized host-side on every
    process. Identity-to-numpy single-process."""
    if jax.process_count() <= 1:
        import numpy as np

        return jax.tree_util.tree_map(
            lambda a: np.asarray(jax.device_get(a)), x
        )
    from jax.experimental import multihost_utils

    return multihost_utils.process_allgather(x, tiled=tiled)


def make_global_array_from_host(x, sharding):
    """Global jax.Array from a host value EVERY process already holds.

    `jax.device_put(host_value, cross_process_sharding)` broadcasts the
    bytes from process 0 over the wire (and the CPU backend's gloo
    transport aborts on the interleaved small transfers a whole pytree
    produces). When the host value is identical on all processes —
    restored checkpoint bytes, same-seed init — no transfer is needed at
    all: each process feeds its OWN addressable shards from its local
    copy via `make_array_from_callback`. Single-process this degenerates
    to a plain sharded device_put."""
    import numpy as np

    arr = np.asarray(x)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx]
    )


def make_array_from_process_local_data(sharding, local_data, global_shape=None):
    """`jax.make_array_from_process_local_data` across versions: assemble
    a global jax.Array from this process's rows of the batch. On JAX
    trees without the helper (< 0.4.31), falls back to
    `make_array_from_single_device_arrays` over process-index slices of
    the local data — each local device gets its addressable block."""
    fn = getattr(jax, "make_array_from_process_local_data", None)
    if fn is not None:
        return fn(sharding, local_data, global_shape)
    import numpy as np

    local_data = np.asarray(local_data)
    if global_shape is None:
        # the real API infers the global shape by scaling sharded dims;
        # the fallback cannot do that reliably (it would have to guess
        # which dims are process-sharded), so require it explicitly —
        # every in-repo caller passes it
        raise ValueError(
            "make_array_from_process_local_data fallback (JAX < 0.4.31) "
            "requires an explicit global_shape"
        )
    addressable = sharding.addressable_devices_indices_map(tuple(global_shape))
    # map each addressable device's GLOBAL index window into local
    # coordinates: along every process-sharded dim this process owns a
    # contiguous block, offset by the minimum start across its own
    # addressable windows (computed PER DIM — two dims sharded across
    # processes carry two different offsets)
    offsets: dict = {}
    arrays = []
    for dev, idx in addressable.items():
        loc = []
        for d, sl in enumerate(idx):
            start = 0 if sl.start is None else sl.start
            stop = global_shape[d] if sl.stop is None else sl.stop
            if global_shape[d] != local_data.shape[d]:
                if d not in offsets:
                    offsets[d] = min(
                        (0 if s[d].start is None else s[d].start)
                        for s in addressable.values()
                    )
                loc.append(slice(start - offsets[d], stop - offsets[d]))
            else:
                loc.append(sl)
        arrays.append(jax.device_put(local_data[tuple(loc)], dev))
    return jax.make_array_from_single_device_arrays(
        tuple(global_shape), sharding, arrays
    )


# --- device mesh helpers ---------------------------------------------------

def create_hybrid_device_mesh(**kwargs):
    """jax.experimental.mesh_utils.create_hybrid_device_mesh, resolved here
    so parallel/mesh.py stays free of experimental imports."""
    from jax.experimental import mesh_utils

    return mesh_utils.create_hybrid_device_mesh(**kwargs)
