"""Step-cadence scalar logging (migrated from utils/observability.py).

`MetricsLogger` is the training/serving JSONL stream: windowed steps/sec
plus scalar metrics, one device fetch per log call. It predates the
telemetry subsystem and keeps its exact stream format (curve-plotting
scripts under scripts/ consume it); the registry/tracer carry the
structured side.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
import warnings
from typing import List, Optional

import jax
import numpy as np


def per_process_metrics_path(path: str, process_index: int) -> str:
    """The per-process sidecar path for a pod run: process 0 keeps the
    requested path (the curve scripts' historical stream), process i > 0
    writes `<stem>.p<i><ext>` — so federation's live pod view has a
    durable on-disk twin instead of a proc-0-only blind spot."""
    if process_index == 0:
        return path
    stem, ext = os.path.splitext(path)
    return f"{stem}.p{process_index}{ext}"


class MetricsLogger:
    """Step-cadence scalar logging with throughput tracking.

    `process_index` (pod runs) stamps every record with its writer's
    rank; `tail()` serves the recent scalar records (the trainer
    `/statusz` loss-curve tail) from a bounded in-memory ring.
    """

    def __init__(self, jsonl_path: Optional[str] = None, print_every: int = 10,
                 process_index: Optional[int] = None,
                 tail_window: int = 256):
        self.jsonl_path = jsonl_path
        self.print_every = print_every
        self.process_index = process_index
        self._file = open(jsonl_path, "a") if jsonl_path else None
        self._t_last = time.perf_counter()
        self._step_last: Optional[int] = None
        # the tail is written by the training thread and read by the ops
        # plane's HTTP thread (/statusz loss tail): iterating a deque
        # during a concurrent append raises RuntimeError, so both sides
        # take the lock
        self._tail = collections.deque(maxlen=tail_window)
        self._tail_lock = threading.Lock()

    @staticmethod
    def _scalar(key: str, v) -> float:
        """One metric value -> float. Non-scalar arrays are reduced to
        their mean WITH a warning naming the key (historically this was a
        bare `float(np.asarray(v))`, which raises an opaque TypeError on
        any size>1 array); an empty array has no defensible scalar and
        raises a clear error instead."""
        arr = np.asarray(jax.device_get(v))
        if arr.size == 1:
            return float(arr.reshape(()))
        if arr.size == 0:
            raise ValueError(
                f"metric {key!r} is an empty array (shape {arr.shape}); "
                "log a scalar or a non-empty array"
            )
        warnings.warn(
            f"metric {key!r} has shape {arr.shape}; logging its mean — "
            "pass a scalar (or reduce explicitly) to silence this",
            stacklevel=3,
        )
        return float(arr.mean())

    def log(self, step: int, metrics: dict):
        """Record metrics for `step`. Values may be jax arrays (fetched here,
        one device sync per call) or plain numbers."""
        now = time.perf_counter()
        vals = {k: self._scalar(k, v) for k, v in metrics.items()}
        # throughput only when the step actually advanced (a second log call
        # at the same step — e.g. eval scores — must not zero it out)
        if self._step_last is not None and step > self._step_last and now > self._t_last:
            vals["steps_per_sec"] = (step - self._step_last) / (now - self._t_last)
            self._t_last, self._step_last = now, step
        elif self._step_last is None or step > self._step_last:
            self._t_last, self._step_last = now, step

        record = {"step": step, **{k: round(v, 6) for k, v in vals.items()}}
        if self.process_index is not None:
            record["process_index"] = self.process_index
        with self._tail_lock:
            self._tail.append(record)
        if self._file is not None:
            self._file.write(json.dumps(record) + "\n")
            self._file.flush()
        if step % self.print_every == 0:
            parts = "  ".join(f"{k} {v:.4f}" for k, v in vals.items())
            print(f"step {step}  {parts}")
        return vals

    def event(self, step: int, kind: str, **fields):
        """Structured non-scalar record (restart causes, preemptions,
        config changes): JSON-serializable fields pass through verbatim —
        no float coercion — into the same JSONL stream, tagged with
        `"event"` so curve-plotting consumers can filter them out.
        Always printed: events are rare and operationally load-bearing.
        """
        record = {"step": step, "event": kind, **fields}
        if self.process_index is not None:
            record["process_index"] = self.process_index
        if self._file is not None:
            self._file.write(json.dumps(record) + "\n")
            self._file.flush()
        parts = "  ".join(f"{k}={v}" for k, v in fields.items())
        print(f"step {step}  [{kind}]  {parts}")
        return record

    def tail(self, n: Optional[int] = None) -> List[dict]:
        """The most recent scalar records (newest last) — the live
        loss-curve tail the trainer ops plane serves on /statusz."""
        with self._tail_lock:
            records = list(self._tail)
        return records[-n:] if n is not None else records

    def close(self):
        # idempotent: context-manager exit followed by an explicit close()
        # (or two owners sharing one logger) must not hit a closed file
        if self._file is not None:
            self._file.close()
            self._file = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
