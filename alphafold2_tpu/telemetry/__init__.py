"""Telemetry subsystem: tracing, metrics, profiling hooks, regression gate.

One unified observability layer (ISSUE 4; ScaleFold arxiv 2404.11068 and
ParaFold arxiv 2111.06340 both credit phase-level measurement for their
scaling results):

  * `trace`     — span-based tracer; Chrome trace-event / Perfetto JSON
                  and JSONL exporters; `NULL_TRACER` no-op default.
  * `registry`  — counters / gauges / histograms with Prometheus text
                  exposition and JSON snapshots; `LatencyHistogram` lives
                  here now.
  * `logger`    — `MetricsLogger`, the step-cadence JSONL stream
                  (migrated from utils/observability.py).
  * `profiling` — compile-event tracking, device-memory / host-memory /
                  analytic-FLOPs gauges, the jax.profiler `profile_trace`
                  wrapper.
  * `check`     — perf-regression gate CLI
                  (`python -m alphafold2_tpu.telemetry.check`).
  * `slo`       — declarative SLO objectives evaluated as fast/slow
                  burn rates over registry deltas; alerts land back in
                  the registry and in a structured event log.
  * `ops_plane` — the LIVE operations plane: stdlib HTTP server
                  (`/metrics`, `/healthz`, `/statusz`) + the incident
                  flight recorder (`serve.py --ops-port/--flight-dir`).
  * `goodput`   — the TRAINING observability plane: wall-clock goodput/
                  badput ledger, pod-wide metric federation with a
                  `process` label, straggler/data-stall detection, and
                  the trainer ops-plane wiring (`train_*.py --ops-port`).
  * `costs`     — the SERVING cost plane: per-executable chip-cost
                  ledger (analytic FLOPs x priced residency x measured
                  EMA per (pool, bucket, schedule, arm, dtype) cell),
                  per-replica serve-goodput ledger, and the exemplar
                  flight book behind `/explainz` — the capacity model
                  the fleet's headroom gauges and the autoscaler's
                  `up_headroom` trigger consume.

Everything is disabled-by-default at the call sites: an engine or
trainer built without a tracer/registry runs the shared no-op singletons
and pays one boolean test per instrumentation point.

docs/OBSERVABILITY.md is the operator guide (span taxonomy, metric
names, how to open traces, how the gate reads baselines).
"""

from alphafold2_tpu.telemetry.goodput import (
    BUCKETS,
    NULL_TRAIN_TELEMETRY,
    FederatedRegistryView,
    GoodputLedger,
    MetricFederation,
    StragglerDetector,
    TrainTelemetry,
    add_observability_args,
    build_train_telemetry,
    observability_enabled,
    relabeled_exposition,
)
from alphafold2_tpu.telemetry.logger import (
    MetricsLogger,
    per_process_metrics_path,
)
from alphafold2_tpu.telemetry.costs import (
    SERVE_CAUSES,
    ExecutableCostLedger,
    FlightBook,
    ServeGoodputLedger,
)
from alphafold2_tpu.telemetry.ops_plane import (
    FlightRecorder,
    OpsServer,
    ProfileBusyError,
    ProfileCapturer,
    ProfileRateLimitedError,
    ops_server_for_engine,
    ops_server_for_fleet,
)
from alphafold2_tpu.telemetry.profiling import (
    CompileTracker,
    device_memory_gauges,
    flops_gauges,
    host_memory_gauges,
    profile_trace,
)
from alphafold2_tpu.telemetry.registry import (
    NULL_REGISTRY,
    Counter,
    Gauge,
    Histogram,
    LatencyHistogram,
    MetricRegistry,
    flatten_snapshot,
    parse_prometheus_text,
)
from alphafold2_tpu.telemetry.slo import (
    SloConfig,
    SloEngine,
    SloObjective,
    default_slo_config,
)
from alphafold2_tpu.telemetry.trace import NULL_TRACER, Tracer, new_trace_id


def add_telemetry_args(ap):
    """The telemetry argparse block shared by train_pre.py,
    train_end2end.py, serve.py, and predict.py — one place to add the
    next knob."""
    ap.add_argument("--trace-out", default=None, metavar="PATH",
                    help="write a Chrome trace-event JSON of this run's "
                         "phase spans here (open in Perfetto / "
                         "chrome://tracing); tracing is off (near-zero "
                         "cost) when unset")
    ap.add_argument("--trace-max-spans", type=int, default=100_000,
                    help="span retention bound; overflow is counted, "
                         "not silently discarded")


def tracer_from_args(args) -> Tracer:
    """A live tracer when --trace-out was given, NULL_TRACER otherwise."""
    if getattr(args, "trace_out", None):
        return Tracer(enabled=True, max_spans=args.trace_max_spans)
    return NULL_TRACER


def finish_trace(tracer: Tracer, args):
    """Export the trace at the end of a CLI run (no-op without
    --trace-out)."""
    if getattr(args, "trace_out", None) and tracer.enabled:
        tracer.export_chrome(args.trace_out)
        n = tracer.span_count
        print(f"wrote {args.trace_out} ({n} span(s)"
              + (f", {tracer.dropped} dropped" if tracer.dropped else "")
              + ")")


__all__ = [
    "BUCKETS",
    "CompileTracker",
    "Counter",
    "ExecutableCostLedger",
    "FederatedRegistryView",
    "FlightBook",
    "FlightRecorder",
    "Gauge",
    "GoodputLedger",
    "Histogram",
    "LatencyHistogram",
    "MetricFederation",
    "MetricRegistry",
    "MetricsLogger",
    "NULL_REGISTRY",
    "NULL_TRACER",
    "NULL_TRAIN_TELEMETRY",
    "OpsServer",
    "ProfileBusyError",
    "ProfileCapturer",
    "ProfileRateLimitedError",
    "SERVE_CAUSES",
    "ServeGoodputLedger",
    "StragglerDetector",
    "TrainTelemetry",
    "SloConfig",
    "SloEngine",
    "SloObjective",
    "Tracer",
    "add_observability_args",
    "add_telemetry_args",
    "build_train_telemetry",
    "default_slo_config",
    "device_memory_gauges",
    "finish_trace",
    "flatten_snapshot",
    "flops_gauges",
    "host_memory_gauges",
    "new_trace_id",
    "observability_enabled",
    "ops_server_for_engine",
    "ops_server_for_fleet",
    "parse_prometheus_text",
    "per_process_metrics_path",
    "profile_trace",
    "relabeled_exposition",
    "tracer_from_args",
]
