"""Perf-regression gate: compare a bench snapshot against a baseline.

``python -m alphafold2_tpu.telemetry.check --current BENCH_r05.json \
      --baseline BENCH_r04.json``

The repo accumulates perf artifacts (`BENCH_*.json` from the bench
driver, `BASELINE.json`, `serve.py --stats-json` snapshots, raw
`bench.py` stdout lines) but nothing ever FAILED when a hot path got
slower. This gate turns those artifacts into an enforced contract:
every metric present in both current and baseline is compared under a
per-metric tolerance rule, and a regression beyond tolerance exits
nonzero — CI-gateable, like `analysis --strict`.

Accepted snapshot formats (auto-detected, see `load_metrics`):
  * bench-driver artifact: {"n", "cmd", "parsed": {...}} — the `parsed`
    result line is used;
  * raw bench.py result line: {"metric": name, "value": v, ...extras};
  * BASELINE.json: {"metric": ..., "published": {...}} — the `published`
    table (may be empty: a baseline with nothing published gates
    nothing and passes, loudly);
  * sweep artifacts (PERF_SWEEP.jsonl / a single sweep row): JSON-lines
    of {"bench": leg, "result": {...}} — every leg's numeric results
    key under "<leg>.<metric>", PLATFORM-QUALIFIED to
    "<leg>.<platform>[.<backend_arm>].<metric>" when the row carries
    the cross-backend matrix fields (latest row per leg+platform+arm
    wins; error/skip rows are dropped), so e.g.
    `disp_flash_attention_xla_ref.cpu.xla_ref.sec_per_iter` gates
    against CPU baselines ONLY — a CPU row can never diff against a TPU
    row of the same leg. Multi-line workers record LIST results (the
    micro kernel grid): each element keys under
    "<leg>.<its string fields>.<metric>" and gates like any scalar leg;
  * any nested dict of numerics (engine stats / registry snapshots),
    flattened to dotted paths.

Direction is inferred from the metric name (`_RULES`, first match wins;
override per-run with --rule); metrics with no inferable direction are
reported informationally, never gated — a gate that guesses directions
would fail builds on improvements.

`--loss-curve` switches the gate to CONVERGENCE mode: --current and
--baseline are training metrics JSONL streams (MetricsLogger format),
each reduced by `load_loss_curve` to smoothed final-window loss, slope,
and best loss, then compared under the loss-curve direction rules — a
diverging run fails the build exactly like a slow step would (ROADMAP
item 1's "loss-curve telemetry wired into the regression gate").

Exit codes: 0 = no regression (including "nothing comparable"),
1 = at least one regression beyond tolerance, 2 = usage/artifact error.
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import sys
from typing import Dict, List, Optional, Tuple

from alphafold2_tpu.telemetry.registry import flatten_snapshot

#: (name glob, direction, default relative tolerance). First match wins.
#: "higher" = bigger is better (a drop beyond tol is a regression);
#: "lower" = smaller is better (a rise beyond tol is a regression);
#: "ignore" = informational only. The ignore block comes FIRST: absolute
#: volume counts (request/compile/observation counts, window sizes,
#: lifetime sums, uptime) scale with how much traffic the snapshot saw,
#: not with how fast the system was — gating them would fail comparisons
#: between runs of different length at identical performance.
_RULES: Tuple[Tuple[str, str, float], ...] = (
    # training-plane efficiency rules come BEFORE the volume-ignore
    # block deliberately: badput seconds end in `_total`-style names but
    # ARE the gated quantity on fixed-length goodput legs (a rise in
    # data-stall badput at identical steps is precisely the regression).
    # badput/stall precede the ratio rule, and the ratio rule is the
    # FULL `goodput_ratio` token: the train_goodput leg prefixes every
    # metric with the leg name, so a bare *goodput* would claim its
    # badput/wall rows too and gate them backwards
    # incident/event VOLUME counters (train_incidents_total{kind=
    # "train_data_stall"}, flight_incidents_total) must stay
    # informational even though their kind labels contain "stall":
    # they scale with run length and chaos plans, not speed
    ("*incidents_total*", "ignore", 0.0),
    # length-adaptive fleet routing (ISSUE 14): routed counts/fractions
    # are traffic COMPOSITION, not speed — a trace with more long
    # sequences legitimately routes more to the SP pool. Placed before
    # the volume-ignores only for documentation locality; same verdict.
    ("*routed*", "ignore", 0.0),
    # per-capability-pool queue wait (the per-pool autoscaling signal):
    # lower is better, and it must gate even though the global
    # *_seconds* rule would also catch it — the pool label is the point
    # (a saturated SP pool hides inside a healthy global p95)
    ("*pool_queue_wait*", "lower", 0.25),
    ("*badput*", "lower", 0.25),
    # the fidelity cascade (ISSUE 19): escalation rate is traffic
    # COMPOSITION — a trace with more hard sequences legitimately
    # escalates more; it must stay informational or a harder trace would
    # read as a regression. Placed before *chip_seconds* so the rate row
    # never falls through to a speed rule. Per-request chip cost is the
    # gated cascade quantity, pinned explicitly (the generic
    # *chip_seconds* rule below would also catch it, but the cascade
    # bench gates at -30% and the doc trail should say so here).
    ("*escalation_rate*", "ignore", 0.0),
    ("*chip_seconds_per_request*", "lower", 0.25),
    # the serving cost plane (ISSUE 15): per-request chip cost gates
    # lower-better (it would also hit the generic *_seconds* rule, but
    # the explicit entry pins intent and a tighter doc trail); capacity
    # headroom and the serving goodput ratio gate higher-better at the
    # same noise-tolerant 25% as the training ratios — chip-free rows
    # are machine-speed-dominated, structural regressions move far more
    ("*chip_seconds*", "lower", 0.25),
    ("*headroom*", "higher", 0.25),
    ("*serve_goodput*", "higher", 0.25),
    # 25%, not the 5-10% of the steady-state throughput rules: the
    # chip-free train_goodput leg's ratio is compile-dominated on a CPU
    # host (machine-speed noise), while a structural regression — a
    # re-serialized pipeline, a checkpoint stampede — moves it far more
    ("*goodput_ratio*", "higher", 0.25),
    ("*stall*", "lower", 0.25),
    ("*skew*", "lower", 0.25),
    # loss-curve gate metrics (--loss-curve mode). The raw signed slope
    # is reported but NOT gated: a healthy converged baseline has slope
    # near (or crossing) zero, where relative change is noise-or-
    # infinite; the gated trend is the dimensionless end/start ratio of
    # the smoothed final window, which a divergence moves far past any
    # smoothing jitter
    ("*loss_final*", "lower", 0.10),
    ("*loss_best*", "lower", 0.10),
    ("*loss_trend*", "lower", 0.10),
    ("*loss_slope*", "ignore", 0.0),
    ("*count*", "ignore", 0.0),
    ("*window*", "ignore", 0.0),
    ("*.sum", "ignore", 0.0),
    ("*total*", "ignore", 0.0),
    ("*uptime*", "ignore", 0.0),
    # cumulative histogram-bucket counters (registry snapshots flatten
    # them under ...buckets.<le>): traffic volume, and their names carry
    # the parent histogram's *_seconds* — without this rule they would
    # gate as latencies
    ("*buckets*", "ignore", 0.0),
    # SLO verdict metrics (slo_burn_rate / slo_alert_active): operational
    # state, not run speed — two runs of different length or chaos plans
    # legitimately differ
    ("*slo_*", "ignore", 0.0),
    # raw residency byte counts are static configuration properties, not
    # run speed; the RATIO below is the gated residency metric
    ("*weight_hbm_bytes*", "ignore", 0.0),
    # QUALITY metrics (the quant_parity leg and future eval legs):
    # bigger is better — without these rules the generic *latency*-style
    # fallthroughs would either skip them or gate them backwards
    ("*contact_precision*", "higher", 0.05),
    ("*lddt*", "higher", 0.05),
    ("*weight_hbm_ratio*", "higher", 0.05),
    ("*quant_weight_ratio*", "higher", 0.05),
    # divergence-from-reference metrics: smaller is better
    ("*distogram_kl*", "lower", 0.25),
    # multi-host scale-out parity (the multihost_dp dryrun leg): the
    # pod's throughput ratio vs the single-process twin — higher is
    # better, and a drop means the cross-process path regressed
    ("*scaling_efficiency*", "higher", 0.10),
    # disaggregated-serving featurization overlap (the featurize_overlap
    # chip-free leg): (featurize busy + execute busy) / wall — > 1 means
    # CPU feature prep genuinely overlapped accelerator dispatch; a drop
    # means the tier re-serialized
    ("*overlap_ratio*", "higher", 0.10),
    ("*steps_per_sec*", "higher", 0.10),
    ("*per_sec*", "higher", 0.10),
    ("*mfu*", "higher", 0.10),
    ("*tflops*", "higher", 0.10),
    ("*hit_rate*", "higher", 0.10),
    ("*occupancy*", "higher", 0.10),
    ("*vs_baseline*", "higher", 0.10),
    ("*sec_per_step*", "lower", 0.15),
    ("*sec_per_iter*", "lower", 0.15),
    ("*sec_per_protein*", "lower", 0.15),
    ("*latency*", "lower", 0.15),
    ("*_seconds*", "lower", 0.15),
    ("*.p50", "lower", 0.15),
    ("*.p95", "lower", 0.25),
    ("*.p99", "lower", 0.25),
)


def rule_for(name: str, rules=_RULES) -> Optional[Tuple[str, float]]:
    low = name.lower()
    for pattern, direction, tol in rules:
        if fnmatch.fnmatch(low, pattern):
            return direction, tol
    return None


def _sweep_rows_to_metrics(rows) -> Dict[str, float]:
    """Sweep rows ({"bench": leg, "result": {...}}) -> flat metrics.

    Later rows win per (leg, platform, arm) — a re-run supersedes its
    predecessor; rows with a null/error result or a structured skip
    contribute nothing. Multi-line workers (the micro kernel grid)
    record a LIST result — each element gates too, qualified by ALL its
    string fields joined in key-sorted order (dir/path/platform/shape ->
    e.g. `micro_kernel.fwd.kernel.tpu.B32_n1152_h8_dh64.sec_per_iter`),
    and regression-gates like any scalar leg; publish exactly that
    produced name into BASELINE.json (compare() intersects names), not
    a hand-reordered one.

    PLATFORM QUALIFICATION (the cross-backend matrix contract): a scalar
    result carrying BOTH the `platform` and `backend_arm` string fields
    keys under `<leg>.<platform>.<backend_arm>.<metric>` — so a CPU
    `xla_ref` row can NEVER gate against a TPU `pallas_tpu` baseline of
    the same leg (disjoint names fall out of compare()'s intersection),
    and the same leg accumulates one gateable trajectory PER backend.
    Rows recorded before the matrix existed carry no `backend_arm`
    field (some carry `platform` alone) and keep their historical
    unqualified names — requiring both fields is what keeps published
    baselines of those legs gating until the leg re-records under the
    matrix contract."""
    flat: Dict[str, float] = {}

    def add(prefix: str, res: dict, qualify: bool) -> None:
        if "skipped" in res:
            return
        if qualify:
            # list elements need distinct names: qualify by the
            # element's string fields (stable — worker grids are
            # deterministic code); platform/backend_arm are among them.
            ident = ".".join(
                res[k] for k in sorted(res) if isinstance(res[k], str)
            )
            if ident:
                prefix = f"{prefix}.{ident}"
        elif (isinstance(res.get("platform"), str)
                and isinstance(res.get("backend_arm"), str)):
            # scalar matrix rows: platform + arm qualification only —
            # the rest of their historical names must stay stable
            prefix = (f"{prefix}.{res['platform']}"
                      f".{res['backend_arm']}")
        for k, v in res.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                flat[f"{prefix}.{k}"] = float(v)

    for e in rows:
        if not isinstance(e, dict) or not isinstance(e.get("bench"), str):
            continue
        res = e.get("result")
        if isinstance(res, dict):
            add(e["bench"], res, qualify=False)
        elif isinstance(res, list):
            for item in res:
                if isinstance(item, dict):
                    add(e["bench"], item, qualify=True)
    return flat


def load_metrics(path_or_dict) -> Dict[str, float]:
    """One snapshot (path or already-parsed dict) -> flat {name: float}."""
    if isinstance(path_or_dict, dict):
        d = path_or_dict
    else:
        with open(path_or_dict) as fh:
            text = fh.read()
        try:
            d = json.loads(text)
        except json.JSONDecodeError:
            # JSON-lines sweep artifact (PERF_SWEEP.jsonl): one JSON
            # object per line; tolerate torn/blank lines (a wedged worker
            # can die mid-write)
            rows = []
            for line in text.splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    rows.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
            if not rows:
                raise ValueError(
                    f"{path_or_dict}: neither a JSON object nor JSON lines"
                ) from None
            return _sweep_rows_to_metrics(rows)
        if not isinstance(d, dict):
            raise ValueError(f"{path_or_dict}: expected a JSON object, got "
                             f"{type(d).__name__}")
    if isinstance(d.get("bench"), str):  # a single sweep row
        return _sweep_rows_to_metrics([d])
    if isinstance(d.get("parsed"), dict):  # bench-driver artifact
        d = d["parsed"]
    if isinstance(d.get("published"), dict):  # BASELINE.json
        d = d["published"]
    if isinstance(d.get("metric"), str) and "value" in d:
        # raw bench.py line: the headline value keys under its metric
        # name; numeric extras (sec_per_step, mfu, ...) keep their keys
        flat = {k: float(v) for k, v in d.items()
                if isinstance(v, (int, float)) and not isinstance(v, bool)
                and k != "value"}
        if isinstance(d["value"], (int, float)):
            flat[d["metric"]] = float(d["value"])
        return flat
    return flatten_snapshot(d)


def load_loss_curve(path, *, key: str = "loss",
                    window: Optional[int] = None,
                    smooth: float = 0.9) -> Dict[str, float]:
    """A training-metrics JSONL stream -> the loss-curve gate metrics.

    Reads the `MetricsLogger` JSONL format (scalar records; `event`
    records skipped), EMA-smooths the `key` series, and reduces it to:

      * `loss_final`  — mean smoothed loss over the final window
        (default: the last quarter of the curve, >= 3 points);
      * `loss_trend`  — smoothed final-window END / START ratio:
        ~<= 1 for plateau-or-improving, > 1 diverging. This is the
        gated slope signal — dimensionless and bounded away from the
        zero crossing, where the raw slope's relative change is
        noise-or-infinite;
      * `loss_slope`  — least-squares slope (loss per step) of the
        smoothed final window: negative = still improving, positive =
        diverging (reported for operators; deliberately not gated —
        see `_RULES`);
      * `loss_best`   — the best (minimum) smoothed loss anywhere on the
        curve — a run that improved then blew up keeps a good best but a
        bad final, so the pair separates divergence from plateau;
      * `points_count` — curve length (informational: *count* rule).

    Gated like any perf leg via the `*loss_final*` / `*loss_trend*` /
    `*loss_best*` direction rules — convergence quality regresses a
    build exactly the way a slow step does.
    """
    steps: List[float] = []
    values: List[float] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail line: same tolerance as sweep rows
            if not isinstance(rec, dict) or "event" in rec:
                continue
            v = rec.get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                steps.append(float(rec.get("step", len(steps))))
                values.append(float(v))
    if len(values) < 3:
        raise ValueError(
            f"{path}: found {len(values)} {key!r} points; a loss curve "
            f"needs at least 3 (is this a metrics JSONL, and is "
            f"--loss-key right?)"
        )
    if not 0.0 <= smooth < 1.0:
        raise ValueError(f"smooth must be in [0, 1), got {smooth}")
    if window is not None and window < 1:
        # ValueError (not a raw ZeroDivisionError / silently sign-flipped
        # means) so the CLI reports it as the documented exit code 2
        raise ValueError(f"window must be >= 1, got {window}")
    smoothed, ema = [], values[0]
    for v in values:
        ema = smooth * ema + (1.0 - smooth) * v
        smoothed.append(ema)
    w = window if window is not None else max(3, len(values) // 4)
    w = min(w, len(values))
    tail_x, tail_y = steps[-w:], smoothed[-w:]
    mx = sum(tail_x) / w
    my = sum(tail_y) / w
    var = sum((x - mx) ** 2 for x in tail_x)
    slope = (
        sum((x - mx) * (y - my) for x, y in zip(tail_x, tail_y)) / var
        if var > 0 else 0.0
    )
    return {
        "loss_final": my,
        "loss_trend": tail_y[-1] / max(abs(tail_y[0]), 1e-12),
        "loss_slope": slope,
        "loss_best": min(smoothed),
        "points_count": float(len(values)),
    }


def compare(current: Dict[str, float], baseline: Dict[str, float],
            tolerance: Optional[float] = None,
            rules=_RULES) -> List[dict]:
    """Per-metric verdicts over the intersection of the two snapshots.

    Each row: {metric, baseline, current, change (signed relative),
    direction, tolerance, status} with status one of "ok" (within
    tolerance or improved), "regressed", "ungated" (no direction rule).
    Metrics present on one side only are omitted — the gate enforces
    metrics, it does not enforce coverage (use --require-overlap for
    that).
    """
    rows = []
    for name in sorted(set(current) & set(baseline)):
        base, cur = baseline[name], current[name]
        rule = rule_for(name, rules)
        if rule is not None and rule[0] == "ignore":
            rule = None
        change = (cur - base) / abs(base) if base else (
            0.0 if cur == base else float("inf") if cur > base
            else float("-inf")
        )
        if rule is None:
            rows.append({"metric": name, "baseline": base, "current": cur,
                         "change": change, "direction": None,
                         "tolerance": None, "status": "ungated"})
            continue
        direction, tol = rule
        if tolerance is not None:
            tol = tolerance
        # signed "badness": positive when moving the wrong way
        bad = -change if direction == "higher" else change
        status = "regressed" if bad > tol else "ok"
        rows.append({"metric": name, "baseline": base, "current": cur,
                     "change": change, "direction": direction,
                     "tolerance": tol, "status": status})
    return rows


def check(current, baseline, tolerance: Optional[float] = None,
          rules=_RULES) -> Tuple[bool, List[dict]]:
    """Python API: (passed, rows). `current`/`baseline` are paths or
    dicts in any accepted format."""
    rows = compare(load_metrics(current), load_metrics(baseline),
                   tolerance=tolerance, rules=rules)
    return not any(r["status"] == "regressed" for r in rows), rows


def _parse_rule(spec: str) -> Tuple[str, str, float]:
    # "pattern=direction:tolerance", e.g. "*latency*=lower:0.2"
    try:
        pattern, rest = spec.split("=", 1)
        direction, tol = rest.split(":", 1)
        if direction not in ("higher", "lower", "ignore"):
            raise ValueError
        return pattern.lower(), direction, float(tol)
    except ValueError:
        raise SystemExit(
            f"--rule {spec!r}: expected PATTERN=higher|lower|ignore:TOLERANCE"
        ) from None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m alphafold2_tpu.telemetry.check",
        description="perf-regression gate over bench/stats snapshots",
    )
    ap.add_argument("--current", required=True,
                    help="snapshot under test (bench artifact, raw bench "
                         "line, stats-json, ...)")
    ap.add_argument("--baseline", required=True,
                    help="reference snapshot (BASELINE.json / BENCH_*.json "
                         "/ a previous stats-json)")
    ap.add_argument("--loss-curve", action="store_true",
                    help="treat --current/--baseline as training metrics "
                         "JSONL streams and gate CONVERGENCE: smoothed "
                         "final-window loss, slope, and best loss "
                         "compared under the loss-curve direction rules")
    ap.add_argument("--loss-key", default="loss",
                    help="JSONL field holding the curve (--loss-curve "
                         "mode; default: loss)")
    ap.add_argument("--loss-window", type=int, default=None,
                    help="final-window size in points (--loss-curve "
                         "mode; default: the last quarter of the curve)")
    ap.add_argument("--loss-smooth", type=float, default=0.9,
                    help="EMA smoothing factor for the curve "
                         "(--loss-curve mode)")
    ap.add_argument("--tolerance", type=float, default=None,
                    help="override every rule's relative tolerance")
    ap.add_argument("--rule", action="append", default=[],
                    metavar="PATTERN=DIR:TOL",
                    help="prepend a direction rule (first match wins), "
                         "e.g. '*latency*=lower:0.2'; repeatable")
    ap.add_argument("--require-overlap", action="store_true",
                    help="fail (exit 1) when the snapshots share no gated "
                         "metric — for CI lanes where silence means the "
                         "bench broke")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    args = ap.parse_args(argv)

    rules = tuple(_parse_rule(s) for s in args.rule) + _RULES
    try:
        if args.loss_curve:
            current = load_loss_curve(
                args.current, key=args.loss_key,
                window=args.loss_window, smooth=args.loss_smooth)
            baseline = load_loss_curve(
                args.baseline, key=args.loss_key,
                window=args.loss_window, smooth=args.loss_smooth)
        else:
            current, baseline = args.current, args.baseline
        passed, rows = check(current, baseline,
                             tolerance=args.tolerance, rules=rules)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"telemetry.check: cannot load snapshots: {e}",
              file=sys.stderr)
        return 2

    gated = [r for r in rows if r["direction"] is not None]
    if args.format == "json":
        print(json.dumps({"passed": passed, "results": rows}, indent=2))
    else:
        for r in rows:
            mark = {"ok": "ok  ", "regressed": "FAIL", "ungated": "info"}
            print(f"[{mark[r['status']]}] {r['metric']}: "
                  f"{r['baseline']:g} -> {r['current']:g} "
                  f"({r['change']:+.1%})"
                  + (f" [{r['direction']} better, tol "
                     f"{r['tolerance']:.0%}]" if r["direction"] else ""))
        if not rows:
            print("telemetry.check: no metric present in both snapshots; "
                  "nothing gated")
        elif not gated:
            print("telemetry.check: no direction rule matched any shared "
                  "metric; nothing gated")
        print(f"telemetry.check: {'PASS' if passed else 'REGRESSION'} "
              f"({len(gated)} gated, "
              f"{sum(r['status'] == 'regressed' for r in rows)} regressed, "
              f"{len(rows) - len(gated)} informational)")
    if args.require_overlap and not gated:
        print("telemetry.check: --require-overlap set and no gated overlap",
              file=sys.stderr)
        return 1
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())
