"""SLO engine: declarative objectives evaluated as burn rates, live.

The registry (telemetry/registry.py) says what HAS happened; nothing in
the stack watched it WHILE traffic flowed — an operator learned the
fleet was shedding from the end-of-run stats dump. This module closes
that loop with the standard SRE machinery (multi-window burn-rate
alerting): objectives are declared as data, evaluated periodically over
registry deltas, and alert transitions land back in the registry (so
`/metrics` scrapes see them) and in a bounded structured-event log (so
the flight recorder can bundle them).

Two objective kinds cover the serving tier's SLOs:

  * ``ratio`` — bad-events / total-events over a trailing window,
    from COUNTER DELTAS (two timestamped samples of the matching
    series). `objective` is the success target (0.99 availability =>
    a 0.01 error budget); burn rate = observed_ratio / budget, the
    "how many times faster than sustainable are we spending the
    budget" number. Covers error-ratio and shed-rate.
  * ``quantile`` — a histogram percentile (its sliding window is
    already recency-weighted) against an absolute `threshold`; burn
    rate = value / threshold. Covers queue-wait p95.

An alert FIRES when both the fast and the slow window burn exceed their
thresholds (`fast_burn` / `slow_burn`): the fast window gives response
time, the slow window keeps a brief blip from paging. It RESOLVES when
the fast window recovers. Each transition increments
`slo_alerts_total{objective,transition}`, flips
`slo_alert_active{objective}`, appends a structured event, and calls
`on_page` (the flight-recorder incident seam) on firing.

Metric selectors are `{"metric": name, "labels": {k: v}}`: every series
of `metric` whose labels are a superset of `labels` is summed — so
`fleet_requests_total{outcome="shed"}` selects exactly the shed
counter while `{"metric": "serving_errors_total"}` sums every error
code. Config is JSON-loadable (`SloConfig.from_file`); unknown keys
reject loudly (the faults --check stance: a typo'd objective must not
silently never fire). Schema: docs/OBSERVABILITY.md "SLO config".

Deterministic by construction: the clock is injectable and
`evaluate(now=...)` is a pure step of the state machine, so tests drive
fast/slow windows without sleeping. Production wiring runs `evaluate()`
on the ops-plane ticker (telemetry/ops_plane.py).
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from collections import deque
from typing import Dict, Optional, Tuple

from alphafold2_tpu.telemetry.registry import LabelsKey, MetricRegistry

_OBJECTIVE_KEYS = {
    "name", "kind", "bad", "total", "objective", "fast_burn", "slow_burn",
    "metric", "labels", "quantile", "threshold",
}
_CONFIG_KEYS = {"fast_window_s", "slow_window_s", "objectives"}


def _selector(spec) -> Tuple[str, LabelsKey]:
    """Normalize one {"metric": ..., "labels": {...}} selector."""
    if isinstance(spec, str):
        return spec, ()
    unknown = set(spec) - {"metric", "labels"}
    if unknown:
        raise ValueError(f"unknown selector key(s) {sorted(unknown)}")
    labels = spec.get("labels", {})
    return str(spec["metric"]), tuple(
        sorted((str(k), str(v)) for k, v in labels.items())
    )


@dataclasses.dataclass(frozen=True)
class SloObjective:
    """One declarative objective (see module docstring for semantics)."""

    name: str
    kind: str                       # "ratio" | "quantile"
    # ratio:
    bad: Tuple[Tuple[str, LabelsKey], ...] = ()
    total: Tuple[Tuple[str, LabelsKey], ...] = ()
    objective: float = 0.99         # success target; budget = 1 - objective
    # quantile:
    metric: str = ""
    labels: LabelsKey = ()
    quantile: float = 0.95
    threshold: float = 1.0          # absolute bound on the percentile
    # both:
    fast_burn: float = 2.0          # firing threshold, fast window
    slow_burn: float = 1.0          # firing threshold, slow window

    def __post_init__(self):
        if self.kind not in ("ratio", "quantile"):
            raise ValueError(
                f"objective {self.name!r}: kind must be 'ratio' or "
                f"'quantile', got {self.kind!r}"
            )
        if self.kind == "ratio":
            if not self.bad or not self.total:
                raise ValueError(
                    f"ratio objective {self.name!r} needs both `bad` and "
                    f"`total` selectors"
                )
            if not (0.0 < self.objective < 1.0):
                raise ValueError(
                    f"objective {self.name!r}: success target must be in "
                    f"(0, 1), got {self.objective}"
                )
        else:
            if not self.metric:
                raise ValueError(
                    f"quantile objective {self.name!r} needs `metric`"
                )
            if self.threshold <= 0:
                raise ValueError(
                    f"objective {self.name!r}: threshold must be positive, "
                    f"got {self.threshold}"
                )
        if self.fast_burn <= 0 or self.slow_burn <= 0:
            raise ValueError(
                f"objective {self.name!r}: burn thresholds must be positive"
            )

    @classmethod
    def from_dict(cls, d: dict) -> "SloObjective":
        unknown = set(d) - _OBJECTIVE_KEYS
        if unknown:
            raise ValueError(
                f"objective {d.get('name', '?')!r}: unknown key(s) "
                f"{sorted(unknown)}; known: {sorted(_OBJECTIVE_KEYS)}"
            )
        kw = dict(d)
        for key in ("bad", "total"):
            if key in kw:
                kw[key] = tuple(_selector(s) for s in kw[key])
        if "labels" in kw:
            kw["labels"] = tuple(
                sorted((str(k), str(v)) for k, v in kw["labels"].items())
            )
        return cls(**kw)


@dataclasses.dataclass(frozen=True)
class SloConfig:
    """Objectives plus the two shared burn windows."""

    objectives: Tuple[SloObjective, ...]
    fast_window_s: float = 60.0
    slow_window_s: float = 300.0

    def __post_init__(self):
        if not (0 < self.fast_window_s <= self.slow_window_s):
            raise ValueError(
                f"need 0 < fast_window_s <= slow_window_s, got "
                f"{self.fast_window_s}/{self.slow_window_s}"
            )
        names = [o.name for o in self.objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names in {names}")

    @classmethod
    def from_dict(cls, d: dict) -> "SloConfig":
        unknown = set(d) - _CONFIG_KEYS
        if unknown:
            raise ValueError(
                f"unknown SLO config key(s) {sorted(unknown)}; known: "
                f"{sorted(_CONFIG_KEYS)}"
            )
        kw = dict(d)
        kw["objectives"] = tuple(
            SloObjective.from_dict(o) for o in d.get("objectives", ())
        )
        return cls(**kw)

    @classmethod
    def from_file(cls, path: str) -> "SloConfig":
        with open(path) as fh:
            return cls.from_dict(json.load(fh))


def default_slo_config(prefix: str = "fleet",
                       fast_window_s: float = 60.0,
                       slow_window_s: float = 300.0) -> SloConfig:
    """The serving tier's stock objectives over the `fleet_*` (fleet
    mode) or `serving_*` (single-engine) metric families — what
    `serve.py --ops-port` arms when no --slo-config is given."""
    if prefix == "fleet":
        total = ({"metric": "fleet_requests_total",
                  "labels": {"outcome": "submitted"}},)
        objectives = (
            SloObjective.from_dict({
                "name": "availability", "kind": "ratio",
                "bad": [{"metric": "fleet_requests_total",
                         "labels": {"outcome": "failed"}}],
                "total": list(total), "objective": 0.999,
                "fast_burn": 14.0, "slow_burn": 6.0,
            }),
            SloObjective.from_dict({
                "name": "shed_rate", "kind": "ratio",
                "bad": [{"metric": "fleet_requests_total",
                         "labels": {"outcome": "shed"}}],
                "total": list(total), "objective": 0.99,
                "fast_burn": 14.0, "slow_burn": 6.0,
            }),
            SloObjective.from_dict({
                "name": "queue_wait_p95", "kind": "quantile",
                "metric": "fleet_queue_wait_seconds", "quantile": 0.95,
                "threshold": 5.0, "fast_burn": 2.0, "slow_burn": 1.0,
            }),
        )
    else:
        total = ({"metric": "serving_requests_total",
                  "labels": {"outcome": "submitted"}},)
        objectives = (
            SloObjective.from_dict({
                "name": "availability", "kind": "ratio",
                "bad": [{"metric": "serving_requests_total",
                         "labels": {"outcome": "failed"}}],
                "total": list(total), "objective": 0.999,
                "fast_burn": 14.0, "slow_burn": 6.0,
            }),
            SloObjective.from_dict({
                "name": "shed_rate", "kind": "ratio",
                "bad": [{"metric": "serving_requests_total",
                         "labels": {"outcome": "rejected"}}],
                "total": list(total), "objective": 0.99,
                "fast_burn": 14.0, "slow_burn": 6.0,
            }),
            SloObjective.from_dict({
                "name": "latency_p95", "kind": "quantile",
                "metric": "serving_request_latency_seconds",
                "quantile": 0.95, "threshold": 30.0,
                "fast_burn": 2.0, "slow_burn": 1.0,
            }),
        )
    return SloConfig(objectives=objectives, fast_window_s=fast_window_s,
                     slow_window_s=slow_window_s)


class _AlertState:
    __slots__ = ("active", "fired_at")

    def __init__(self):
        self.active = False
        self.fired_at: Optional[float] = None


class SloEngine:
    """Evaluates an `SloConfig` against one registry; see module docstring.

    Args:
      registry: the registry whose counters/histograms the objectives
        select from — AND where the slo_* result metrics are recorded,
        so one `/metrics` scrape carries both the signals and the
        verdicts.
      config: `SloConfig`.
      on_page: optional `fn(objective_name, transition, info)` called on
        every transition ("firing" / "resolved") OUTSIDE the engine
        lock; exceptions are swallowed with a traceback (the flight
        recorder plugs in here).
      clock: injectable monotonic clock (tests pin time).
      max_events: structured-event retention bound.
    """

    def __init__(self, registry: MetricRegistry, config: SloConfig,
                 on_page=None, clock=time.monotonic, max_events: int = 512):
        self.registry = registry
        self.config = config
        self.on_page = on_page
        self._clock = clock
        self._lock = threading.Lock()
        # timestamped counter samples; retention covers the slow window
        # (+1 sample of slack so a full-window delta is always available)
        self._samples: deque = deque()
        # per-objective burn history for quantile kinds: (ts, burn)
        self._burn_hist: Dict[str, deque] = {
            o.name: deque() for o in config.objectives
        }
        self._alerts: Dict[str, _AlertState] = {
            o.name: _AlertState() for o in config.objectives
        }
        self._events: deque = deque(maxlen=max_events)
        for o in config.objectives:
            # pre-register so a scrape before the first transition still
            # shows the families (absence of slo_alert_active reads as
            # "no SLO engine", not "no alert")
            self.registry.gauge(
                "slo_alert_active", help="1 = objective currently firing",
                objective=o.name).set(0)

    # ------------------------------------------------------------ sampling

    @staticmethod
    def _counter_sample(families) -> Dict[Tuple[str, LabelsKey], float]:
        out: Dict[Tuple[str, LabelsKey], float] = {}
        for name, (kind, series) in families.items():
            if kind != "counter" or name.startswith("slo_"):
                continue
            for key, metric in series.items():
                out[(name, key)] = metric.value
        return out

    @staticmethod
    def _select(sample: Dict[Tuple[str, LabelsKey], float],
                selectors) -> float:
        total = 0.0
        for name, want in selectors:
            want_d = dict(want)
            for (n, key), v in sample.items():
                if n != name:
                    continue
                have = dict(key)
                if all(have.get(k) == val for k, val in want_d.items()):
                    total += v
        return total

    def _delta_ratio(self, obj: SloObjective, window_s: float,
                     now: float) -> float:
        """bad/total over the trailing window, from counter deltas. With
        history shorter than the window, the oldest sample is used — an
        honest partial window beats silence at startup."""
        current = self._samples[-1][1]
        past = self._samples[0][1]
        for ts, sample in self._samples:
            if ts <= now - window_s:
                past = sample
            else:
                break
        d_bad = max(
            0.0, self._select(current, obj.bad) - self._select(past, obj.bad)
        )
        d_total = max(
            0.0,
            self._select(current, obj.total) - self._select(past, obj.total),
        )
        # bad and total move at DIFFERENT times (submit vs terminal): a
        # window where only failures land — submissions stopped because
        # the service is down — must read as full burn, not zero traffic
        d_total = max(d_total, d_bad)
        return (d_bad / d_total) if d_total > 0 else 0.0

    @staticmethod
    def _quantile_value(obj: SloObjective, families) -> float:
        fam = families.get(obj.metric)
        if fam is None or fam[0] != "histogram":
            return 0.0
        want = dict(obj.labels)
        best = 0.0
        for key, metric in fam[1].items():
            have = dict(key)
            if all(have.get(k) == v for k, v in want.items()):
                best = max(best, metric.percentile(obj.quantile * 100.0))
        return best

    @staticmethod
    def _window_burn(hist: deque, window_s: float, now: float) -> float:
        """Mean of the recorded instantaneous burns inside the window."""
        vals = [b for ts, b in hist if ts >= now - window_s]
        return (sum(vals) / len(vals)) if vals else 0.0

    # ----------------------------------------------------------- evaluate

    def evaluate(self, now: Optional[float] = None) -> dict:
        """One evaluation pass: sample counters, compute each objective's
        fast/slow burn, run the alert state machine. Returns
        {objective: {burn_fast, burn_slow, active}}. Thread-safe;
        `on_page` callbacks run outside the lock."""
        now = self._clock() if now is None else now
        pages = []
        # one registry sweep per tick: both the counter sample and every
        # quantile objective read from this snapshot
        families = self.registry.collect()
        with self._lock:
            self._samples.append((now, self._counter_sample(families)))
            horizon = now - self.config.slow_window_s
            while len(self._samples) > 2 and self._samples[1][0] <= horizon:
                self._samples.popleft()
            out = {}
            for obj in self.config.objectives:
                if obj.kind == "ratio":
                    budget = 1.0 - obj.objective
                    burn_fast = self._delta_ratio(
                        obj, self.config.fast_window_s, now) / budget
                    burn_slow = self._delta_ratio(
                        obj, self.config.slow_window_s, now) / budget
                else:
                    inst = self._quantile_value(obj, families) / obj.threshold
                    hist = self._burn_hist[obj.name]
                    hist.append((now, inst))
                    while hist and hist[0][0] < horizon:
                        hist.popleft()
                    burn_fast = self._window_burn(
                        hist, self.config.fast_window_s, now)
                    burn_slow = self._window_burn(
                        hist, self.config.slow_window_s, now)
                for window, burn in (("fast", burn_fast), ("slow", burn_slow)):
                    self.registry.gauge(
                        "slo_burn_rate",
                        help="error-budget burn rate (1.0 = spending "
                             "exactly the budget)",
                        objective=obj.name, window=window).set(burn)
                state = self._alerts[obj.name]
                should_fire = (burn_fast >= obj.fast_burn
                               and burn_slow >= obj.slow_burn)
                should_resolve = state.active and burn_fast < obj.fast_burn
                transition = None
                if should_fire and not state.active:
                    state.active, state.fired_at = True, now
                    transition = "firing"
                elif should_resolve:
                    state.active, state.fired_at = False, None
                    transition = "resolved"
                if transition is not None:
                    self.registry.counter(
                        "slo_alerts_total",
                        help="SLO alert transitions",
                        objective=obj.name, transition=transition).inc()
                    self.registry.gauge(
                        "slo_alert_active",
                        help="1 = objective currently firing",
                        objective=obj.name).set(1 if state.active else 0)
                    info = {
                        "ts": now,
                        "objective": obj.name,
                        "transition": transition,
                        "burn_fast": round(burn_fast, 4),
                        "burn_slow": round(burn_slow, 4),
                        # "objective_kind", not "kind": the flight
                        # recorder splats this dict into incident(kind=
                        # "slo_page", **info) — a "kind" key collides
                        "objective_kind": obj.kind,
                    }
                    self._events.append(info)
                    pages.append((obj.name, transition, info))
                out[obj.name] = {
                    "burn_fast": burn_fast,
                    "burn_slow": burn_slow,
                    "active": state.active,
                }
        for name, transition, info in pages:
            if self.on_page is not None:
                try:
                    self.on_page(name, transition, info)
                except Exception:  # noqa: BLE001 — paging must not kill
                    # the evaluator thread
                    import traceback

                    traceback.print_exc()
        return out

    # -------------------------------------------------------------- stats

    def events(self) -> list:
        """The structured transition log (oldest first, bounded)."""
        with self._lock:
            return list(self._events)

    def snapshot(self) -> dict:
        """JSON-ready state: per-objective active flags + recent events
        (the `/statusz` payload)."""
        with self._lock:
            return {
                "fast_window_s": self.config.fast_window_s,
                "slow_window_s": self.config.slow_window_s,
                "objectives": {
                    o.name: {
                        "kind": o.kind,
                        "active": self._alerts[o.name].active,
                    }
                    for o in self.config.objectives
                },
                "events": list(self._events)[-32:],
            }
