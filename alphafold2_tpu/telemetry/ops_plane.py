"""The live operations plane: HTTP observability + incident flight recorder.

Everything the telemetry subsystem measures was, until this module,
post-hoc — metrics rode end-of-run `stats()` dumps and spans rode
`--trace-out` exports. This module makes the stack OPERABLE while it
runs, with two cooperating pieces, both stdlib-only (an inference fleet
must not grow an HTTP-framework dependency for three read-only
endpoints):

`OpsServer` — a threaded `http.server` exposing:

  * ``/metrics``  Prometheus text exposition (v0.0.4) of one registry —
                  the scrape target; round-trips through
                  `registry.parse_prometheus_text`.
  * ``/healthz``  liveness JSON from the serving tier's `health()`
                  (HealthMonitor states + replica-up view for the
                  fleet, worker/breaker state for one engine). HTTP 200
                  while status is "ok"/"degraded", 503 when "down" —
                  load balancers need the status CODE, not JSON parsing.
  * ``/statusz``  the deep-dive JSON: health + full stats snapshot +
                  registry snapshot + span summary + SLO state + flight
                  recorder state.

  plus a background TICKER thread that drives the periodic work live
  observability needs: `SloEngine.evaluate()`, `FlightRecorder.poll()`
  (metric-delta events), and any extra `add_tick` callables (serve.py
  adds host-memory gauges). Construction binds the socket (port 0 =
  ephemeral, `.port` reports the real one) but nothing runs until
  `start()`.

`FlightRecorder` — the incident black box. A bounded in-memory ring of
recent operational events (incidents, SLO transitions, metric deltas)
rides along for free; when an incident TRIPS — breaker open, replica
drain, watchdog fire, SLO page, all wired through the existing
reliability seams (`ServingEngine(incident_hook=)`,
`ServingFleet(incident_hook=)`, `SloEngine(on_page=)`) — it snapshots a
forensic bundle to disk: the event ring, the tail of the span stream
(trace_ids included, so the victim request's cross-replica life is in
the bundle), the registry snapshot, and an optional stats payload.
Bundles are rate-limited per incident kind (`min_interval_s`): a breaker
flapping at 10 Hz must not turn the recorder into a disk-filling
incident of its own (suppressed bundles are still ring events and
counted).

Wiring: `serve.py --ops-port/--flight-dir/--slo-config`, helpers
`ops_server_for_engine` / `ops_server_for_fleet` below; the TRAINERS
mount the same server through `telemetry.goodput.build_train_telemetry`
(`train_pre.py` / `train_end2end.py --ops-port`, with the goodput
ledger's progress watchdog as `/healthz` and — on a pod — the federated
`process`-labeled registry view as `/metrics`).
docs/OBSERVABILITY.md "The operations plane" is the operator guide;
docs/OPERATIONS.md maps each alert to its first diagnostic step.
"""

from __future__ import annotations

import json
import os
import threading
import time
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, List, Optional

from alphafold2_tpu.telemetry.registry import MetricRegistry
from alphafold2_tpu.telemetry.trace import NULL_TRACER, Tracer

#: incident kinds the stack's seams report today (an unknown kind is
#: still recorded — this list is documentation, not a gate)
KNOWN_INCIDENT_KINDS = (
    "breaker_open",     # engine circuit transitioned to open
    "replica_drain",    # fleet health monitor took a replica out
    "watchdog_fire",    # hung-batch watchdog abandoned a dispatch
    "slo_page",         # an SLO objective started firing
    "scale_up",         # autoscaler grew the replica pool
    "scale_down",       # autoscaler retired a replica
    "featurize_worker_death",  # a featurize worker thread died (respawned)
    "train_straggler",  # one pod process's step time diverged from the rest
    "train_data_stall",  # the input pipeline stalled training (local fetch
    #                      share or pod fetch skew past threshold)
)


class FlightRecorder:
    """Bounded event ring + incident bundle writer (see module docstring).

    Args:
      out_dir: where bundles land (created lazily on first incident).
      tracer: span source for the bundle tail (`NULL_TRACER` = no spans).
      registry: metric source for delta events and bundle snapshots; the
        recorder also counts itself here (`flight_incidents_total{kind}`,
        `flight_bundles_written_total`). None disables both.
      stats_fn: optional zero-arg callable whose JSON-ready return value
        is embedded in each bundle (an engine/fleet `stats`).
      capacity: event-ring bound.
      span_tail: how many of the most recent spans a bundle carries.
      min_interval_s: per-kind bundle rate limit; suppressed incidents
        are ring events only.
      clock: wall clock for bundle timestamps (injectable for tests).
    """

    def __init__(self, out_dir: str, *, tracer: Tracer = NULL_TRACER,
                 registry: Optional[MetricRegistry] = None, stats_fn=None,
                 capacity: int = 1024, span_tail: int = 512,
                 min_interval_s: float = 5.0, clock=time.time):
        if capacity < 1 or span_tail < 0:
            raise ValueError(
                f"capacity must be >= 1 and span_tail >= 0, got "
                f"{capacity}/{span_tail}"
            )
        self.out_dir = out_dir
        self._tracer = tracer
        self._registry = registry
        self._stats_fn = stats_fn
        self._span_tail = span_tail
        self._min_interval_s = min_interval_s
        self._clock = clock
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self._capacity = capacity
        self._seq = 0                  # bundle sequence number
        self._last_bundle_at = {}      # kind -> wall ts of last bundle
        self._bundles: List[str] = []  # paths written this process
        self._suppressed = 0
        self._last_counters = None     # poll() delta baseline

    def bind(self, *, registry: Optional[MetricRegistry] = None,
             stats_fn=None):
        """Late wiring for the construction-order cycle: the recorder
        must exist BEFORE the engine/fleet (it is their incident_hook),
        but the engine owns the registry and stats the bundles embed."""
        if registry is not None:
            self._registry = registry
        if stats_fn is not None:
            self._stats_fn = stats_fn

    # ------------------------------------------------------------- events

    def note(self, kind: str, **attrs):
        """Append one event to the ring (no disk I/O)."""
        with self._lock:
            self._events.append(
                {"ts": self._clock(), "kind": kind, "attrs": attrs}
            )
            if len(self._events) > self._capacity:
                del self._events[: len(self._events) - self._capacity]

    def poll(self):
        """Ticker hook: record which counters moved since the last poll
        as one `metrics_delta` ring event — the bundle's answer to "what
        was happening in the minute before the incident" even when spans
        are off."""
        if self._registry is None:
            return
        current = {}
        for name, (kind, series) in self._registry.collect().items():
            if kind != "counter":
                continue
            for key, metric in series.items():
                current[(name, key)] = metric.value
        with self._lock:
            last, self._last_counters = self._last_counters, current
        if last is None:
            return
        deltas = {}
        for (name, key), v in current.items():
            d = v - last.get((name, key), 0.0)
            if d:
                label = name + "".join(f"{{{k}={val}}}" for k, val in key)
                deltas[label] = d
        if deltas:
            self.note("metrics_delta", deltas=deltas)

    # ----------------------------------------------------------- incidents

    def incident(self, kind: str, **attrs) -> Optional[str]:
        """One incident: ring event + (rate limits permitting) a bundle
        on disk. Returns the bundle path, or None when suppressed.
        Never raises — the recorder is called from reliability seams
        that must keep serving through a full disk."""
        now = self._clock()
        self.note("incident:" + kind, **attrs)
        if self._registry is not None:
            self._registry.counter(
                "flight_incidents_total", help="incidents by kind",
                kind=kind).inc()
        with self._lock:
            last = self._last_bundle_at.get(kind)
            if last is not None and now - last < self._min_interval_s:
                self._suppressed += 1
                return None
            self._last_bundle_at[kind] = now
            self._seq += 1
            seq = self._seq
        try:
            return self._write_bundle(seq, kind, attrs, now)
        except Exception:  # noqa: BLE001 — see docstring
            traceback.print_exc()
            return None

    def _write_bundle(self, seq: int, kind: str, attrs: dict,
                      now: float) -> str:
        bundle = {
            "incident": {"seq": seq, "kind": kind, "ts": now,
                         "attrs": attrs},
            "events": None,   # filled under the lock below
            "spans": self._tracer.spans(last=self._span_tail),
        }
        with self._lock:
            bundle["events"] = list(self._events)
        if self._registry is not None:
            bundle["metrics"] = self._registry.snapshot()
        if self._stats_fn is not None:
            try:
                bundle["stats"] = self._stats_fn()
            except Exception:  # noqa: BLE001 — a failing stats provider
                # must not cost the rest of the bundle
                bundle["stats_error"] = traceback.format_exc()
        os.makedirs(self.out_dir, exist_ok=True)
        path = os.path.join(self.out_dir, f"incident-{seq:03d}-{kind}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(bundle, fh, indent=1, default=str)
        os.replace(tmp, path)  # atomic: a reader never sees a torn bundle
        if self._registry is not None:
            self._registry.counter(
                "flight_bundles_written_total",
                help="forensic bundles snapshotted to disk").inc()
        with self._lock:
            self._bundles.append(path)
        return path

    def slo_page_hook(self, objective: str, transition: str, info: dict):
        """Adapter matching `SloEngine(on_page=...)`: a FIRING transition
        is an incident (bundle), a RESOLVED transition is a ring event."""
        # info already carries objective/transition keys (slo.py builds
        # it that way) — merge rather than re-pass, or the duplicate
        # kwarg would TypeError and the page would never bundle
        attrs = dict(info)
        attrs.setdefault("objective", objective)
        if transition == "firing":
            self.incident("slo_page", **attrs)
        else:
            self.note("slo_" + transition, **attrs)

    # -------------------------------------------------------------- stats

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "dir": self.out_dir,
                "events": len(self._events),
                "bundles": list(self._bundles),
                "suppressed_bundles": self._suppressed,
            }


class _Handler(BaseHTTPRequestHandler):
    """One request; the server instance carries the providers."""

    server_version = "af2-ops/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: ARG002 — silence stdout;
        # scrape-per-second access logs are noise in a serving console
        pass

    def _send(self, code: int, body: bytes, content_type: str):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, payload):
        self._send(code, json.dumps(payload, indent=1, default=str)
                   .encode("utf-8"), "application/json")

    def do_GET(self):  # noqa: N802 — http.server API
        ops: "OpsServer" = self.server.ops  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            if path == "/metrics":
                body = ops.registry.to_prometheus().encode("utf-8")
                ops.registry.counter(
                    "ops_scrapes_total",
                    help="/metrics scrapes served").inc()
                self._send(200, body,
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                payload = ops.health()
                code = 503 if payload.get("status") == "down" else 200
                self._send_json(code, payload)
            elif path == "/statusz":
                self._send_json(200, ops.statusz())
            elif path == "/":
                self._send_json(200, {"endpoints": [
                    "/metrics", "/healthz", "/statusz"]})
            else:
                self._send_json(404, {"error": f"no such endpoint {path!r}"})
        except Exception:  # noqa: BLE001 — a handler bug must answer 500,
            # not silently drop the connection
            self._send(500, traceback.format_exc().encode("utf-8"),
                       "text/plain; charset=utf-8")


class OpsServer:
    """The observability HTTP server + periodic ticker (module docstring).

    Construction BINDS the port (so `.port` is real immediately and a
    bind failure surfaces at build, not mid-traffic) but serves nothing
    until `start()`. `stop()` is idempotent and joins both threads.
    """

    def __init__(self, *, registry: MetricRegistry,
                 health_fn: Optional[Callable[[], dict]] = None,
                 stats_fn: Optional[Callable[[], dict]] = None,
                 tracer: Tracer = NULL_TRACER,
                 slo=None, recorder: Optional[FlightRecorder] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 tick_interval_s: float = 1.0):
        if tick_interval_s <= 0:
            raise ValueError(
                f"tick_interval_s must be positive, got {tick_interval_s}"
            )
        self.registry = registry
        self._health_fn = health_fn
        self._stats_fn = stats_fn
        self._tracer = tracer
        self.slo = slo
        self.recorder = recorder
        self._tick_interval_s = tick_interval_s
        self._extra_ticks: List[Callable[[], None]] = []
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.ops = self  # type: ignore[attr-defined]
        self._serve_thread: Optional[threading.Thread] = None
        self._tick_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ------------------------------------------------------------ address

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host = self._httpd.server_address[0]
        return f"http://{host}:{self.port}"

    # ----------------------------------------------------------- payloads

    def health(self) -> dict:
        if self._health_fn is None:
            return {"status": "ok"}
        return self._health_fn()

    def statusz(self) -> dict:
        out = {
            "health": self.health(),
            "metrics": self.registry.snapshot(),
            "spans": self._tracer.summary(),
        }
        if self._stats_fn is not None:
            out["stats"] = self._stats_fn()
        if self.slo is not None:
            out["slo"] = self.slo.snapshot()
        if self.recorder is not None:
            out["flight_recorder"] = self.recorder.snapshot()
        return out

    # ------------------------------------------------------------ lifecycle

    def add_tick(self, fn: Callable[[], None]):
        """Register an extra periodic callable on the ticker thread."""
        self._extra_ticks.append(fn)

    def tick(self):
        """One ticker pass (tests call it directly; the thread loops it).
        Each hook is isolated: one raising hook must not starve the
        others or kill the ticker."""
        hooks: List[Callable[[], None]] = []
        if self.slo is not None:
            hooks.append(self.slo.evaluate)
        if self.recorder is not None:
            hooks.append(self.recorder.poll)
        hooks.extend(self._extra_ticks)
        for fn in hooks:
            try:
                fn()
            except Exception:  # noqa: BLE001 — see docstring
                traceback.print_exc()

    def start(self):
        if self._serve_thread is not None:
            return
        self._stop.clear()
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever, name="ops-plane-http",
            daemon=True)
        self._serve_thread.start()

        def tick_loop():
            while not self._stop.wait(self._tick_interval_s):
                self.tick()

        self._tick_thread = threading.Thread(
            target=tick_loop, name="ops-plane-ticker", daemon=True)
        self._tick_thread.start()

    def stop(self, timeout: Optional[float] = 5.0):
        self._stop.set()
        if self._tick_thread is not None:
            self._tick_thread.join(timeout)
            self._tick_thread = None
        if self._serve_thread is not None:
            # shutdown() blocks on an event only serve_forever() sets —
            # calling it on a built-but-never-started server deadlocks
            self._httpd.shutdown()
            self._serve_thread.join(timeout)
            self._serve_thread = None
        self._httpd.server_close()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


def ops_server_for_engine(engine, *, tracer: Tracer = NULL_TRACER,
                          slo=None, recorder: Optional[FlightRecorder] = None,
                          host: str = "127.0.0.1", port: int = 0,
                          tick_interval_s: float = 1.0) -> OpsServer:
    """Wire an `OpsServer` over one `ServingEngine`: its metrics
    registry, `health()`, and `stats()`."""
    return OpsServer(
        registry=engine.metrics.registry, health_fn=engine.health,
        stats_fn=engine.stats, tracer=tracer, slo=slo, recorder=recorder,
        host=host, port=port, tick_interval_s=tick_interval_s,
    )


def ops_server_for_fleet(fleet, *, tracer: Tracer = NULL_TRACER,
                         slo=None, recorder: Optional[FlightRecorder] = None,
                         host: str = "127.0.0.1", port: int = 0,
                         tick_interval_s: float = 1.0) -> OpsServer:
    """Wire an `OpsServer` over a `ServingFleet`: the fleet registry
    (fleet_* families + SLO/flight metrics), `health()` (HealthMonitor +
    replica-up view), and the full fleet `stats()`."""
    return OpsServer(
        registry=fleet.registry, health_fn=fleet.health,
        stats_fn=fleet.stats, tracer=tracer, slo=slo, recorder=recorder,
        host=host, port=port, tick_interval_s=tick_interval_s,
    )
