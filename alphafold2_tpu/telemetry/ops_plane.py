"""The live operations plane: HTTP observability + incident flight recorder.

Everything the telemetry subsystem measures was, until this module,
post-hoc — metrics rode end-of-run `stats()` dumps and spans rode
`--trace-out` exports. This module makes the stack OPERABLE while it
runs, with two cooperating pieces, both stdlib-only (an inference fleet
must not grow an HTTP-framework dependency for three read-only
endpoints):

`OpsServer` — a threaded `http.server` exposing:

  * ``/metrics``  Prometheus text exposition (v0.0.4) of one registry —
                  the scrape target; round-trips through
                  `registry.parse_prometheus_text`.
  * ``/healthz``  liveness JSON from the serving tier's `health()`
                  (HealthMonitor states + replica-up view for the
                  fleet, worker/breaker state for one engine). HTTP 200
                  while status is "ok"/"degraded", 503 when "down" —
                  load balancers need the status CODE, not JSON parsing.
  * ``/statusz``  the deep-dive JSON: health + full stats snapshot +
                  registry snapshot + span summary + SLO state + flight
                  recorder state. A store-armed fleet's stats carry the
                  ``artifact_store`` (hit/miss/corrupt/byte view) and
                  ``frontdoor`` (in-flight keys, waiting followers)
                  sections — the first place to look when the cache hit
                  rate moves (docs/OPERATIONS.md runbook). Fleet servers
                  also carry a ``backpressure`` section (the queue /
                  per-pool / retry-budget ``retry_after_s`` horizons an
                  HTTP front end quotes next to its 429 +
                  ``Retry-After`` sheds).
  * ``/explainz`` exemplar flight lookup (`?trace_id=<id>`): the full
                  per-request flight record from a `telemetry.costs.
                  FlightBook` — every lifecycle event across featurize
                  tier, admission, and replicas. Cache provenance rides
                  the terminal event: an artifact-store hit finishes
                  with ``cache_tier="artifact_store"`` + its level
                  (memory/disk), a coalesced follower with
                  ``coalesced=true`` + its leader's trace_id, and
                  store-served features note ``features_from_store``.
                  Without a trace_id it answers 400 with the most
                  recent ids; an unknown id is 404. Absent entirely (no
                  flight book wired) it is 404.
  * ``/profilez`` on-demand `jax.profiler` capture (`?duration_s=N`,
                  bounded and rate-limited — see `ProfileCapturer`):
                  200 with the capture directory when started, 409 while
                  one is already running, 429 inside the rate-limit
                  window — so the next healthy TPU probe can be profiled
                  WITHOUT redeploying the fleet.
  * ``/threadz``  every live thread (name, daemon flag, current stack
                  via ``sys._current_frames()``) — the first diagnostic
                  for a suspected deadlock; thread names follow the
                  stable ``af2-*`` scheme so the owner of each stack is
                  readable at a glance.

  plus a background TICKER thread that drives the periodic work live
  observability needs: `SloEngine.evaluate()`, `FlightRecorder.poll()`
  (metric-delta events), and any extra `add_tick` callables (serve.py
  adds host-memory gauges). Construction binds the socket (port 0 =
  ephemeral, `.port` reports the real one) but nothing runs until
  `start()`.

`FlightRecorder` — the incident black box. A bounded in-memory ring of
recent operational events (incidents, SLO transitions, metric deltas)
rides along for free; when an incident TRIPS — breaker open, replica
drain, watchdog fire, SLO page, all wired through the existing
reliability seams (`ServingEngine(incident_hook=)`,
`ServingFleet(incident_hook=)`, `SloEngine(on_page=)`) — it snapshots a
forensic bundle to disk: the event ring, the tail of the span stream
(trace_ids included, so the victim request's cross-replica life is in
the bundle), the registry snapshot, and an optional stats payload.
Bundles are rate-limited per incident kind (`min_interval_s`): a breaker
flapping at 10 Hz must not turn the recorder into a disk-filling
incident of its own (suppressed bundles are still ring events and
counted).

Wiring: `serve.py --ops-port/--flight-dir/--slo-config`, helpers
`ops_server_for_engine` / `ops_server_for_fleet` below; the TRAINERS
mount the same server through `telemetry.goodput.build_train_telemetry`
(`train_pre.py` / `train_end2end.py --ops-port`, with the goodput
ledger's progress watchdog as `/healthz` and — on a pod — the federated
`process`-labeled registry view as `/metrics`).
docs/OBSERVABILITY.md "The operations plane" is the operator guide;
docs/OPERATIONS.md maps each alert to its first diagnostic step.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, List, Optional
from urllib.parse import parse_qs, urlsplit

from alphafold2_tpu.telemetry.registry import MetricRegistry
from alphafold2_tpu.telemetry.trace import NULL_TRACER, Tracer

#: incident kinds the stack's seams report today (an unknown kind is
#: still recorded — this list is documentation, not a gate)
KNOWN_INCIDENT_KINDS = (
    "breaker_open",     # engine circuit transitioned to open
    "replica_drain",    # fleet health monitor took a replica out
    "watchdog_fire",    # hung-batch watchdog abandoned a dispatch
    "slo_page",         # an SLO objective started firing
    "scale_up",         # autoscaler grew the replica pool
    "scale_down",       # autoscaler retired a replica
    "featurize_worker_death",  # a featurize worker thread died (respawned)
    "train_straggler",  # one pod process's step time diverged from the rest
    "train_data_stall",  # the input pipeline stalled training (local fetch
    #                      share or pod fetch skew past threshold)
)


class FlightRecorder:
    """Bounded event ring + incident bundle writer (see module docstring).

    Args:
      out_dir: where bundles land (created lazily on first incident).
      tracer: span source for the bundle tail (`NULL_TRACER` = no spans).
      registry: metric source for delta events and bundle snapshots; the
        recorder also counts itself here (`flight_incidents_total{kind}`,
        `flight_bundles_written_total`). None disables both.
      stats_fn: optional zero-arg callable whose JSON-ready return value
        is embedded in each bundle (an engine/fleet `stats`).
      capacity: event-ring bound.
      span_tail: how many of the most recent spans a bundle carries.
      min_interval_s: per-kind bundle rate limit; suppressed incidents
        are ring events only.
      clock: wall clock for bundle timestamps (injectable for tests).
    """

    def __init__(self, out_dir: str, *, tracer: Tracer = NULL_TRACER,
                 registry: Optional[MetricRegistry] = None, stats_fn=None,
                 capacity: int = 1024, span_tail: int = 512,
                 min_interval_s: float = 5.0, clock=time.time):
        if capacity < 1 or span_tail < 0:
            raise ValueError(
                f"capacity must be >= 1 and span_tail >= 0, got "
                f"{capacity}/{span_tail}"
            )
        self.out_dir = out_dir
        self._tracer = tracer
        self._registry = registry
        self._stats_fn = stats_fn
        self._span_tail = span_tail
        self._min_interval_s = min_interval_s
        self._clock = clock
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self._capacity = capacity
        self._seq = 0                  # bundle sequence number
        self._last_bundle_at = {}      # kind -> wall ts of last bundle
        self._bundles: List[str] = []  # paths written this process
        self._suppressed = 0
        self._last_counters = None     # poll() delta baseline

    def bind(self, *, registry: Optional[MetricRegistry] = None,
             stats_fn=None):
        """Late wiring for the construction-order cycle: the recorder
        must exist BEFORE the engine/fleet (it is their incident_hook),
        but the engine owns the registry and stats the bundles embed."""
        if registry is not None:
            self._registry = registry
        if stats_fn is not None:
            self._stats_fn = stats_fn

    # ------------------------------------------------------------- events

    def note(self, kind: str, **attrs):
        """Append one event to the ring (no disk I/O)."""
        with self._lock:
            self._events.append(
                {"ts": self._clock(), "kind": kind, "attrs": attrs}
            )
            if len(self._events) > self._capacity:
                del self._events[: len(self._events) - self._capacity]

    def poll(self):
        """Ticker hook: record which counters moved since the last poll
        as one `metrics_delta` ring event — the bundle's answer to "what
        was happening in the minute before the incident" even when spans
        are off."""
        if self._registry is None:
            return
        current = {}
        for name, (kind, series) in self._registry.collect().items():
            if kind != "counter":
                continue
            for key, metric in series.items():
                current[(name, key)] = metric.value
        with self._lock:
            last, self._last_counters = self._last_counters, current
        if last is None:
            return
        deltas = {}
        for (name, key), v in current.items():
            d = v - last.get((name, key), 0.0)
            if d:
                label = name + "".join(f"{{{k}={val}}}" for k, val in key)
                deltas[label] = d
        if deltas:
            self.note("metrics_delta", deltas=deltas)

    # ----------------------------------------------------------- incidents

    def incident(self, kind: str, **attrs) -> Optional[str]:
        """One incident: ring event + (rate limits permitting) a bundle
        on disk. Returns the bundle path, or None when suppressed.
        Never raises — the recorder is called from reliability seams
        that must keep serving through a full disk."""
        now = self._clock()
        self.note("incident:" + kind, **attrs)
        if self._registry is not None:
            self._registry.counter(
                "flight_incidents_total", help="incidents by kind",
                kind=kind).inc()
        with self._lock:
            last = self._last_bundle_at.get(kind)
            if last is not None and now - last < self._min_interval_s:
                self._suppressed += 1
                return None
            self._last_bundle_at[kind] = now
            self._seq += 1
            seq = self._seq
        try:
            return self._write_bundle(seq, kind, attrs, now)
        except Exception:  # noqa: BLE001 — see docstring
            traceback.print_exc()
            return None

    def _write_bundle(self, seq: int, kind: str, attrs: dict,
                      now: float) -> str:
        bundle = {
            "incident": {"seq": seq, "kind": kind, "ts": now,
                         "attrs": attrs},
            "events": None,   # filled under the lock below
            "spans": self._tracer.spans(last=self._span_tail),
        }
        with self._lock:
            bundle["events"] = list(self._events)
        if self._registry is not None:
            bundle["metrics"] = self._registry.snapshot()
        if self._stats_fn is not None:
            try:
                bundle["stats"] = self._stats_fn()
            except Exception:  # noqa: BLE001 — a failing stats provider
                # must not cost the rest of the bundle
                bundle["stats_error"] = traceback.format_exc()
        os.makedirs(self.out_dir, exist_ok=True)
        path = os.path.join(self.out_dir, f"incident-{seq:03d}-{kind}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(bundle, fh, indent=1, default=str)
        os.replace(tmp, path)  # atomic: a reader never sees a torn bundle
        if self._registry is not None:
            self._registry.counter(
                "flight_bundles_written_total",
                help="forensic bundles snapshotted to disk").inc()
        with self._lock:
            self._bundles.append(path)
        return path

    def slo_page_hook(self, objective: str, transition: str, info: dict):
        """Adapter matching `SloEngine(on_page=...)`: a FIRING transition
        is an incident (bundle), a RESOLVED transition is a ring event."""
        # info already carries objective/transition keys (slo.py builds
        # it that way) — merge rather than re-pass, or the duplicate
        # kwarg would TypeError and the page would never bundle
        attrs = dict(info)
        attrs.setdefault("objective", objective)
        if transition == "firing":
            self.incident("slo_page", **attrs)
        else:
            self.note("slo_" + transition, **attrs)

    # -------------------------------------------------------------- stats

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "dir": self.out_dir,
                "events": len(self._events),
                "bundles": list(self._bundles),
                "suppressed_bundles": self._suppressed,
            }


class ProfileCapturer:
    """On-demand, duration-bounded, rate-limited `jax.profiler` capture
    (the `/profilez` backing; module docstring).

    One capture at a time: `start()` raises `ProfileBusyError` while a
    capture runs (HTTP 409) and `ProfileRateLimitedError` inside
    `min_interval_s` of the previous start (HTTP 429) — an operator
    hammering the endpoint must not turn the profiler into the overload.
    The capture itself runs on a daemon thread: `jax.profiler.
    start_trace` into a fresh `profile-<seq>` directory under `out_dir`,
    stopped after `duration_s` (clamped to `max_duration_s`). Outcomes
    are counted (`profilez_captures_total{outcome}`) so abuse is itself
    scrapeable.
    """

    def __init__(self, out_dir: str, *,
                 registry: Optional[MetricRegistry] = None,
                 max_duration_s: float = 30.0, min_interval_s: float = 30.0,
                 clock=time.monotonic):
        if max_duration_s <= 0 or min_interval_s < 0:
            raise ValueError(
                f"max_duration_s must be > 0 and min_interval_s >= 0, got "
                f"{max_duration_s}/{min_interval_s}")
        self.out_dir = out_dir
        self._registry = registry
        self.max_duration_s = max_duration_s
        self.min_interval_s = min_interval_s
        self._clock = clock
        self._lock = threading.Lock()
        self._running: Optional[dict] = None
        self._last_start: Optional[float] = None
        self._seq = 0
        self._captures: List[dict] = []
        self._abort = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _count(self, outcome: str):
        if self._registry is not None:
            self._registry.counter(
                "profilez_captures_total",
                help="/profilez capture requests by outcome",
                outcome=outcome).inc()

    def start(self, duration_s: float = 2.0) -> dict:
        """Begin one capture; returns {"dir", "duration_s", "seq"}.
        Raises ProfileBusyError / ProfileRateLimitedError /
        ValueError(duration) — the HTTP layer maps them to 409/429/400.

        The capture itself (start_trace -> bounded wait -> stop_trace)
        runs ENTIRELY on one NON-daemon worker thread, asynchronously:

          * asynchronously, because `jax.profiler.start_trace` can block
            for seconds behind an in-flight XLA compile — an HTTP
            handler must answer now, not when the compiler yields;
          * one thread for both ends, NON-daemon, because any daemon
            thread still inside the profiler (blocked start OR pending
            stop) at interpreter teardown SEGFAULTS in native code
            (reproduced on jax 0.4.x CPU): threading._shutdown joins
            non-daemon threads BEFORE teardown, and close() — wired
            into OpsServer.stop — aborts the wait early so exit never
            stalls a full capture window.

        A start_trace failure is counted (`outcome="failed"`) and
        surfaced in `snapshot()` rather than the HTTP response (the
        request already returned)."""
        if duration_s <= 0:
            raise ValueError(
                f"duration_s must be positive, got {duration_s}")
        duration_s = min(float(duration_s), self.max_duration_s)
        now = self._clock()
        with self._lock:
            if self._running is not None:
                self._count("rejected_busy")
                raise ProfileBusyError(
                    f"a profile capture is already running "
                    f"(dir {self._running['dir']})")
            if (self._last_start is not None
                    and now - self._last_start < self.min_interval_s):
                self._count("rejected_rate_limited")
                raise ProfileRateLimitedError(
                    f"last capture started "
                    f"{now - self._last_start:.1f}s ago; minimum interval "
                    f"is {self.min_interval_s}s")
            self._seq += 1
            seq = self._seq
            path = os.path.join(self.out_dir, f"profile-{seq:03d}")
            info = {"seq": seq, "dir": path, "duration_s": duration_s}
            self._running = info
            self._last_start = now
        self._abort.clear()

        def capture():
            try:
                import jax

                os.makedirs(path, exist_ok=True)
                jax.profiler.start_trace(path)
            except Exception:  # noqa: BLE001 — surfaced via snapshot
                traceback.print_exc()
                self._count("failed")
                info["error"] = "start_trace failed (see server log)"
                with self._lock:
                    self._running = None
                    self._captures.append(dict(info))
                return
            self._count("started")
            self._abort.wait(duration_s)
            try:
                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001 — a failing stop must not
                # kill the capture thread silently mid-serving
                traceback.print_exc()
                info["error"] = "stop_trace failed (see server log)"
            finally:
                with self._lock:
                    self._running = None
                    self._captures.append(dict(info))

        self._thread = threading.Thread(
            target=capture, name="af2-profilez-capture", daemon=False)
        self._thread.start()
        return dict(info)

    def close(self, timeout: Optional[float] = 30.0):
        """Abort any in-flight capture and join the capture thread —
        called from `OpsServer.stop()` so a capture can never be left
        racing process teardown (a blocked start_trace can hold the
        join up to roughly one compile; the non-daemon thread covers
        the exit path even if this times out). Idempotent."""
        self._abort.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)
        self._thread = None

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "dir": self.out_dir,
                "running": dict(self._running) if self._running else None,
                "captures": [dict(c) for c in self._captures],
                "max_duration_s": self.max_duration_s,
                "min_interval_s": self.min_interval_s,
            }


class ProfileBusyError(RuntimeError):
    """A capture is already in flight (HTTP 409)."""


class ProfileRateLimitedError(RuntimeError):
    """Too soon after the previous capture (HTTP 429)."""


class _Handler(BaseHTTPRequestHandler):
    """One request; the server instance carries the providers."""

    server_version = "af2-ops/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: ARG002 — silence stdout;
        # scrape-per-second access logs are noise in a serving console
        pass

    def _send(self, code: int, body: bytes, content_type: str):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, payload):
        self._send(code, json.dumps(payload, indent=1, default=str)
                   .encode("utf-8"), "application/json")

    def do_GET(self):  # noqa: N802 — http.server API
        ops: "OpsServer" = self.server.ops  # type: ignore[attr-defined]
        parts = urlsplit(self.path)
        path = parts.path.rstrip("/") or "/"
        query = parse_qs(parts.query)
        try:
            if path == "/metrics":
                body = ops.registry.to_prometheus().encode("utf-8")
                ops.registry.counter(
                    "ops_scrapes_total",
                    help="/metrics scrapes served").inc()
                self._send(200, body,
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                payload = ops.health()
                code = 503 if payload.get("status") == "down" else 200
                self._send_json(code, payload)
            elif path == "/statusz":
                self._send_json(200, ops.statusz())
            elif path == "/explainz":
                code, payload = ops.explainz(
                    query.get("trace_id", [None])[0])
                self._send_json(code, payload)
            elif path == "/profilez":
                code, payload = ops.profilez(
                    query.get("duration_s", [None])[0])
                self._send_json(code, payload)
            elif path == "/threadz":
                self._send_json(200, ops.threadz())
            elif path == "/":
                self._send_json(200, {"endpoints": [
                    "/metrics", "/healthz", "/statusz", "/explainz",
                    "/profilez", "/threadz"]})
            else:
                self._send_json(404, {"error": f"no such endpoint {path!r}"})
        except Exception:  # noqa: BLE001 — a handler bug must answer 500,
            # not silently drop the connection
            self._send(500, traceback.format_exc().encode("utf-8"),
                       "text/plain; charset=utf-8")


class OpsServer:
    """The observability HTTP server + periodic ticker (module docstring).

    Construction BINDS the port (so `.port` is real immediately and a
    bind failure surfaces at build, not mid-traffic) but serves nothing
    until `start()`. `stop()` is idempotent and joins both threads.
    """

    def __init__(self, *, registry: MetricRegistry,
                 health_fn: Optional[Callable[[], dict]] = None,
                 stats_fn: Optional[Callable[[], dict]] = None,
                 backpressure_fn: Optional[Callable[[], dict]] = None,
                 tracer: Tracer = NULL_TRACER,
                 slo=None, recorder: Optional[FlightRecorder] = None,
                 flights=None, profiler: Optional[ProfileCapturer] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 tick_interval_s: float = 1.0):
        if tick_interval_s <= 0:
            raise ValueError(
                f"tick_interval_s must be positive, got {tick_interval_s}"
            )
        self.registry = registry
        self._health_fn = health_fn
        self._stats_fn = stats_fn
        # shed-advice provider (ServingFleet.backpressure): the queue /
        # per-pool / retry-budget retry_after_s horizons a 429-emitting
        # HTTP front end quotes in Retry-After headers
        self._backpressure_fn = backpressure_fn
        self._tracer = tracer
        self.slo = slo
        self.recorder = recorder
        self.flights = flights      # telemetry.costs.FlightBook (/explainz)
        self.profiler = profiler    # ProfileCapturer (/profilez)
        self._dropped_seen = 0
        if tracer.enabled:
            # registered eagerly at 0 so span loss is alertable from the
            # first scrape (the ticker publishes increments; before this
            # counter, retention overflow was visible only in summary()
            # and the Chrome export's otherData)
            registry.counter(
                "trace_spans_dropped_total",
                help="spans lost to the tracer retention bound "
                     "(max_spans) — raise --trace-max-spans if nonzero")
        self._tick_interval_s = tick_interval_s
        self._extra_ticks: List[Callable[[], None]] = []
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.ops = self  # type: ignore[attr-defined]
        self._serve_thread: Optional[threading.Thread] = None
        self._tick_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ------------------------------------------------------------ address

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host = self._httpd.server_address[0]
        return f"http://{host}:{self.port}"

    # ----------------------------------------------------------- payloads

    def health(self) -> dict:
        if self._health_fn is None:
            return {"status": "ok"}
        return self._health_fn()

    def statusz(self) -> dict:
        out = {
            "health": self.health(),
            "metrics": self.registry.snapshot(),
            "spans": self._tracer.summary(),
        }
        if self._stats_fn is not None:
            out["stats"] = self._stats_fn()
        if self._backpressure_fn is not None:
            out["backpressure"] = self._backpressure_fn()
        if self.slo is not None:
            out["slo"] = self.slo.snapshot()
        if self.recorder is not None:
            out["flight_recorder"] = self.recorder.snapshot()
        if self.flights is not None:
            out["flights"] = self.flights.snapshot()
        if self.profiler is not None:
            out["profiler"] = self.profiler.snapshot()
        return out

    def explainz(self, trace_id: Optional[str]):
        """(code, payload) for `/explainz?trace_id=` — the exemplar
        flight lookup (telemetry/costs.py FlightBook)."""
        if self.flights is None:
            return 404, {"error": "no flight book wired on this server"}
        if not trace_id:
            return 400, {
                "error": "pass ?trace_id=<id>",
                "recent_trace_ids": self.flights.recent(),
            }
        rec = self.flights.get(trace_id)
        if rec is None:
            return 404, {
                "error": f"no flight recorded for trace_id {trace_id!r} "
                         f"(evicted, or never seen)",
                "recent_trace_ids": self.flights.recent(),
            }
        return 200, rec

    def threadz(self) -> dict:
        """`/threadz` payload: every live thread with its current stack
        (`sys._current_frames()`) — the FIRST diagnostic for a suspected
        deadlock or hang: two threads parked in `acquire` with crossed
        lock owners is a lock-order inversion caught red-handed (the
        static side of the same contract is af2lint's concurrency pass).
        Served by one of the HTTP pool's own threads, so even a fully
        wedged serving tier still answers."""
        frames = sys._current_frames()
        threads = []
        for t in threading.enumerate():
            frame = frames.get(t.ident)
            stack = [ln.rstrip() for ln in
                     traceback.format_stack(frame)] if frame else []
            threads.append({
                "name": t.name,
                "ident": t.ident,
                "daemon": t.daemon,
                "alive": t.is_alive(),
                "stack": stack,
            })
        threads.sort(key=lambda e: str(e["name"]))
        return {"count": len(threads), "threads": threads}

    def profilez(self, duration_s):
        """(code, payload) for `/profilez?duration_s=` — start one
        bounded jax.profiler capture (409 busy / 429 rate-limited)."""
        if self.profiler is None:
            return 404, {"error": "no profiler wired on this server "
                                  "(serve.py arms it with --flight-dir)"}
        try:
            duration = float(duration_s) if duration_s is not None else 2.0
        except ValueError:
            return 400, {"error": f"duration_s must be a number, got "
                                  f"{duration_s!r}"}
        try:
            info = self.profiler.start(duration)
        except ProfileBusyError as e:
            return 409, {"error": str(e)}
        except ProfileRateLimitedError as e:
            return 429, {"error": str(e)}
        except ValueError as e:
            return 400, {"error": str(e)}
        return 200, {"status": "capturing", **info}

    # ------------------------------------------------------------ lifecycle

    def add_tick(self, fn: Callable[[], None]):
        """Register an extra periodic callable on the ticker thread."""
        self._extra_ticks.append(fn)

    def tick(self):
        """One ticker pass (tests call it directly; the thread loops it).
        Each hook is isolated: one raising hook must not starve the
        others or kill the ticker."""
        hooks: List[Callable[[], None]] = []
        if self.slo is not None:
            hooks.append(self.slo.evaluate)
        if self.recorder is not None:
            hooks.append(self.recorder.poll)
        if self._tracer.enabled:
            hooks.append(self._sync_dropped_spans)
        hooks.extend(self._extra_ticks)
        for fn in hooks:
            try:
                fn()
            except Exception:  # noqa: BLE001 — see docstring
                traceback.print_exc()

    def _sync_dropped_spans(self):
        """Ticker hook: publish tracer retention overflow as the
        monotone `trace_spans_dropped_total` counter (increment-based so
        the counter only grows across tracer instances)."""
        dropped = self._tracer.dropped
        delta = dropped - self._dropped_seen
        if delta > 0:
            self._dropped_seen = dropped
            self.registry.counter(
                "trace_spans_dropped_total",
                help="spans lost to the tracer retention bound "
                     "(max_spans) — raise --trace-max-spans if nonzero"
            ).inc(delta)

    def start(self):
        if self._serve_thread is not None:
            return
        self._stop.clear()
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever, name="af2-ops-http",
            daemon=True)
        self._serve_thread.start()

        def tick_loop():
            while not self._stop.wait(self._tick_interval_s):
                self.tick()

        self._tick_thread = threading.Thread(
            target=tick_loop, name="af2-ops-ticker", daemon=True)
        self._tick_thread.start()

    def stop(self, timeout: Optional[float] = 5.0):
        self._stop.set()
        if self.profiler is not None:
            # an in-flight /profilez capture must resolve before the
            # process can tear down (see ProfileCapturer.close)
            self.profiler.close()
        if self._tick_thread is not None:
            self._tick_thread.join(timeout)
            self._tick_thread = None
        if self._serve_thread is not None:
            # shutdown() blocks on an event only serve_forever() sets —
            # calling it on a built-but-never-started server deadlocks
            self._httpd.shutdown()
            self._serve_thread.join(timeout)
            self._serve_thread = None
        self._httpd.server_close()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


def ops_server_for_engine(engine, *, tracer: Tracer = NULL_TRACER,
                          slo=None, recorder: Optional[FlightRecorder] = None,
                          profiler: Optional[ProfileCapturer] = None,
                          host: str = "127.0.0.1", port: int = 0,
                          tick_interval_s: float = 1.0) -> OpsServer:
    """Wire an `OpsServer` over one `ServingEngine`: its metrics
    registry, `health()`, `stats()`, and its flight book (/explainz)."""
    return OpsServer(
        registry=engine.metrics.registry, health_fn=engine.health,
        stats_fn=engine.stats, tracer=tracer, slo=slo, recorder=recorder,
        flights=getattr(engine, "flights", None), profiler=profiler,
        host=host, port=port, tick_interval_s=tick_interval_s,
    )


def ops_server_for_fleet(fleet, *, tracer: Tracer = NULL_TRACER,
                         slo=None, recorder: Optional[FlightRecorder] = None,
                         profiler: Optional[ProfileCapturer] = None,
                         host: str = "127.0.0.1", port: int = 0,
                         tick_interval_s: float = 1.0) -> OpsServer:
    """Wire an `OpsServer` over a `ServingFleet`: the fleet registry
    (fleet_* families + SLO/flight metrics), `health()` (HealthMonitor +
    replica-up view), the full fleet `stats()`, and the fleet's flight
    book (/explainz)."""
    return OpsServer(
        registry=fleet.registry, health_fn=fleet.health,
        stats_fn=fleet.stats, tracer=tracer, slo=slo, recorder=recorder,
        backpressure_fn=getattr(fleet, "backpressure", None),
        flights=getattr(fleet, "flights", None), profiler=profiler,
        host=host, port=port, tick_interval_s=tick_interval_s,
    )
