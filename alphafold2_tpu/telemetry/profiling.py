"""Profiling hooks: compile-event tracking, device-memory and FLOPs gauges.

ScaleFold (arxiv 2404.11068) got its 10-hour AlphaFold training largely
by measuring and then deleting per-step overheads; the biggest invisible
overheads in this stack are XLA compiles (30+ s per serving bucket, once
per shape) and device-memory pressure. This module makes both visible
through the metric registry and the span tracer:

  * `CompileTracker` — a context manager around any compile site
    (the serving AOT cache, a trainer's warmup step): per-key compile
    count + wall seconds as registry metrics, plus a `compile` span.
  * `device_memory_gauges` — `device.memory_stats()` (TPU/GPU backends;
    returns None on CPU) into `device_memory_bytes{kind=...}` gauges.
  * `flops_gauges` — the analytic model-FLOP count from `utils/flops.py`
    (XLA's own cost analysis undercounts scanned trunks ~100x) as gauges,
    so MFU can be derived from any metrics scrape.
  * `profile_trace` — the jax.profiler context manager (migrated from
    utils/observability.py; re-exported there for back-compat).
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

import jax

from alphafold2_tpu.telemetry.registry import MetricRegistry
from alphafold2_tpu.telemetry.trace import NULL_TRACER, Tracer


@contextlib.contextmanager
def profile_trace(log_dir: str, enabled: bool = True):
    """Capture a jax.profiler trace (XLA device timelines included) into
    `log_dir` for the enclosed step window; view with TensorBoard's profile
    plugin or Perfetto."""
    if not enabled:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class CompileTracker:
    """Compile-event accounting around an AOT cache or a jit warmup.

    ``with tracker.track(bucket=256): exe = jit(f).lower(...).compile()``
    lands, per label set:
      * counter  `<prefix>_total`          — COMPLETED compile events
      * gauge    `<prefix>_seconds_total`  — cumulative wall seconds
      * gauge    `<prefix>_last_seconds`   — most recent compile
      * counter  `<prefix>_failed_total`   — compiles that raised
    and one `compile` span (cat="compile") on the tracer. A failed
    compile (XLA OOM, lowering error) must not read as a completed one —
    only the failure counter moves, and the span carries the `error`
    attribute; the exception propagates unchanged.
    """

    def __init__(self, registry: MetricRegistry, tracer: Tracer = NULL_TRACER,
                 prefix: str = "compile"):
        self.registry = registry
        self.tracer = tracer
        self.prefix = prefix

    @contextlib.contextmanager
    def track(self, **labels):
        with self.tracer.span(self.prefix, cat="compile", **labels):
            t0 = time.perf_counter()
            try:
                yield
            except BaseException:
                self.registry.counter(
                    f"{self.prefix}_failed_total",
                    help="compile attempts that raised", **labels).inc()
                raise
            dt = time.perf_counter() - t0
            self.registry.counter(
                f"{self.prefix}_total",
                help="completed compile events", **labels).inc()
            self.registry.gauge(
                f"{self.prefix}_seconds_total",
                help="cumulative compile wall seconds", **labels).inc(dt)
            self.registry.gauge(
                f"{self.prefix}_last_seconds",
                help="wall seconds of the most recent compile",
                **labels).set(dt)


def host_memory_gauges(registry: MetricRegistry) -> dict:
    """Portable process-memory gauges: `host_memory_bytes{kind=rss}`
    (current resident set, /proc when available) and `{kind=peak_rss}`
    (lifetime peak via `resource.getrusage`). Unlike
    `device_memory_gauges` this NEVER returns None — CPU-only runs get
    host pressure where `device.memory_stats()` is blind — and costs two
    syscalls, so the ops-plane ticker can call it every second.

    Returns {"rss_bytes": ..., "peak_rss_bytes": ...} (0.0 for a field
    the platform cannot report — absence is explicit, never a crash)."""
    peak = rss = 0.0
    try:
        import resource
        import sys

        ru = resource.getrusage(resource.RUSAGE_SELF)
        # ru_maxrss is kilobytes on Linux, bytes on macOS
        peak = float(ru.ru_maxrss) * (1.0 if sys.platform == "darwin"
                                      else 1024.0)
    except (ImportError, OSError):  # resource is POSIX-only
        pass
    try:
        with open("/proc/self/status") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    rss = float(line.split()[1]) * 1024.0  # kB field
                    break
    except OSError:
        rss = peak  # no procfs: peak is the honest upper bound we have
    out = {"rss_bytes": rss, "peak_rss_bytes": peak}
    help_ = "process host memory (resource.getrusage / /proc/self/status)"
    registry.gauge("host_memory_bytes", help=help_, kind="rss").set(rss)
    registry.gauge("host_memory_bytes", help=help_, kind="peak_rss").set(peak)
    return out


def device_memory_gauges(registry: MetricRegistry,
                         device=None) -> Optional[dict]:
    """Record `device.memory_stats()` into gauges; returns the raw stats
    dict, or None when the backend exposes none (CPU) — callers must not
    treat absence as zero memory."""
    dev = device if device is not None else jax.local_devices()[0]
    stats = getattr(dev, "memory_stats", lambda: None)()
    if not stats:
        return None
    for kind, value in stats.items():
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            registry.gauge(
                "device_memory_bytes",
                help="device.memory_stats() fields",
                device=str(dev.id), kind=str(kind),
            ).set(float(value))
    return dict(stats)


def flops_gauges(registry: MetricRegistry, model_cfg, n: int, r: int, c: int,
                 grad_accum: int = 1) -> dict:
    """Analytic per-step FLOP gauges for the configured model workload
    (pair side n, MSA r x c): `model_train_step_flops` and
    `model_forward_flops`. Paired with a measured steps/sec these give
    MFU without trusting XLA's scan-blind cost analysis."""
    from alphafold2_tpu.utils.flops import model_fwd_flops, train_step_flops

    fwd = model_fwd_flops(model_cfg, n, r, c)
    step = train_step_flops(model_cfg, n, r, c, grad_accum=grad_accum)
    registry.gauge(
        "model_forward_flops",
        help="analytic matmul FLOPs of one forward (utils/flops.py)",
    ).set(fwd)
    registry.gauge(
        "model_train_step_flops",
        help="analytic matmul FLOPs of one optimizer step",
    ).set(step)
    return {"forward_flops": fwd, "train_step_flops": step}
