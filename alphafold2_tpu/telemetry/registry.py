"""Metric registry: named counters / gauges / histograms with exposition.

Replaces the ad-hoc dict plumbing that grew inside `serving/metrics.py`
and the trainer loops with one typed, thread-safe registry:

  * `Counter` — monotonically-intended running total (`inc`); negative
    increments are permitted for internal reconciliation (the serving
    engine un-counts a submission that failed to enqueue) but should not
    appear in steady state.
  * `Gauge` — last-written value (`set` / `inc`), e.g. queue depth,
    device memory, per-bucket compile seconds.
  * `Histogram` — sliding-window quantiles over observations, reusing
    `LatencyHistogram` (which lives here now; `utils.observability`
    re-exports it for back-compat), plus LIFETIME cumulative buckets
    (`DEFAULT_BUCKET_BOUNDS`, seconds-oriented) so Prometheus exposition
    is a real `histogram` type — `_bucket{le=...}`/`_sum`/`_count` a
    Prometheus server can `histogram_quantile()` over and aggregate
    across replicas, which summary-quantile gauges cannot.

Exposition: `to_prometheus()` emits Prometheus text format (v0.0.4);
`snapshot()` returns the same data as a JSON-ready dict (histograms
carry both the sliding-window quantiles and the cumulative buckets). A
minimal `parse_prometheus_text` parser lives here too so the round-trip
is testable without a Prometheus server.

Cost contract: `MetricRegistry(enabled=False)` hands every caller a
shared no-op metric — no allocation, no locks, empty snapshots — so
instrumentation stays in hot paths unconditionally.
"""

from __future__ import annotations

import bisect
import collections
import re
import threading
from typing import Dict, Optional, Tuple


class LatencyHistogram:
    """Streaming latency percentiles over a sliding window.

    The serving engine (serving/metrics.py) needs request-latency
    quantiles that (a) track the RECENT traffic mix, not the lifetime mix
    — a bucket-ladder warmup with two 30 s compiles must age out of p99
    once steady-state batches flow — and (b) cost O(window) memory
    regardless of how many requests pass through. A bounded deque of the
    last `window` observations gives both; percentiles are computed by
    nearest-rank over a sorted snapshot (window is small, sorting at
    snapshot time beats maintaining an order statistic per observe()).

    Thread-safe: `observe` is called from the scheduler worker thread
    while `snapshot` is called from health-check/stats readers.
    """

    def __init__(self, window: int = 2048):
        if window <= 0:
            raise ValueError(f"window must be positive, got {window}")
        self._values = collections.deque(maxlen=window)
        self._lock = threading.Lock()
        self._count = 0  # lifetime observations (window evicts, this doesn't)
        self._max = 0.0
        self._sum = 0.0  # lifetime sum (Prometheus summary `_sum`)

    def observe(self, value: float):
        v = float(value)
        with self._lock:
            self._values.append(v)
            self._count += 1
            self._sum += v
            if v > self._max:
                self._max = v

    @staticmethod
    def _percentile(ordered, q: float) -> float:
        # nearest-rank on a pre-sorted list; q in [0, 100]
        if not ordered:
            return 0.0
        idx = min(len(ordered) - 1, int(round(q / 100.0 * (len(ordered) - 1))))
        return ordered[idx]

    def percentile(self, q: float) -> float:
        with self._lock:
            ordered = sorted(self._values)
        return self._percentile(ordered, q)

    def snapshot(self) -> dict:
        """Plain-float summary: count (lifetime), window stats, p50/p95/p99."""
        with self._lock:
            ordered = sorted(self._values)
            count, vmax, vsum = self._count, self._max, self._sum
        return {
            "count": count,
            "window": len(ordered),
            "mean": (sum(ordered) / len(ordered)) if ordered else 0.0,
            "p50": self._percentile(ordered, 50.0),
            "p95": self._percentile(ordered, 95.0),
            "p99": self._percentile(ordered, 99.0),
            "max": vmax,
            "sum": vsum,
        }


_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

LabelsKey = Tuple[Tuple[str, str], ...]


def _labels_key(labels: dict) -> LabelsKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def render_labels(key: LabelsKey) -> str:
    if not key:
        return ""
    body = ",".join(f'{k}="{_escape(v)}"' for k, v in key)
    return "{" + body + "}"


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _unescape(v: str) -> str:
    return (
        v.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")
    )


class Counter:
    __slots__ = ("_lock", "_value")
    kind = "counter"

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1):
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    __slots__ = ("_lock", "_value")
    kind = "gauge"

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float):
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1):
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


#: cumulative-bucket upper bounds (seconds-oriented: the stack's
#: histograms are latencies/waits). +Inf is implicit in exposition.
DEFAULT_BUCKET_BOUNDS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    10.0, 30.0, 60.0, 120.0,
)


def format_le(bound: float) -> str:
    """Prometheus `le` label value: trimmed decimal, `+Inf` sentinel."""
    if bound == float("inf"):
        return "+Inf"
    return format(bound, ".12g")


class Histogram:
    """Sliding-window quantiles + lifetime sum/count, on LatencyHistogram
    internals (composition: the window/percentile machinery is shared with
    every pre-registry call site) — plus LIFETIME cumulative buckets for
    real Prometheus `histogram` exposition. Buckets are cumulative
    counters (never windowed): a scraper computes rates from successive
    scrapes, so the bucket counts must only ever grow."""

    __slots__ = ("_hist", "_bounds", "_bucket_counts", "_bucket_sum",
                 "_bucket_lock")
    kind = "histogram"

    def __init__(self, window: int = 2048,
                 bounds: Tuple[float, ...] = DEFAULT_BUCKET_BOUNDS):
        if list(bounds) != sorted(bounds) or len(set(bounds)) != len(bounds):
            raise ValueError(f"bucket bounds must be strictly increasing, "
                             f"got {bounds}")
        self._hist = LatencyHistogram(window=window)
        self._bounds = tuple(float(b) for b in bounds)
        # per-bound NON-cumulative counts (+ one overflow slot for +Inf);
        # cumulated at read time so observe() stays one increment. The
        # lifetime sum rides the SAME lock so one exposition() read sees
        # buckets/sum/count from the same observation set — Prometheus
        # requires the +Inf bucket to equal _count on every scrape
        self._bucket_counts = [0] * (len(self._bounds) + 1)
        self._bucket_sum = 0.0
        self._bucket_lock = threading.Lock()

    def observe(self, v: float):
        v = float(v)
        self._hist.observe(v)
        i = bisect.bisect_left(self._bounds, v)
        with self._bucket_lock:
            self._bucket_counts[i] += 1
            self._bucket_sum += v

    def percentile(self, q: float) -> float:
        return self._hist.percentile(q)

    def exposition(self) -> Tuple[dict, float, int]:
        """(cumulative buckets incl. +Inf, lifetime sum, lifetime count),
        mutually consistent: read under one lock, with count derived from
        the buckets themselves."""
        with self._bucket_lock:
            counts = list(self._bucket_counts)
            total = self._bucket_sum
        out, running = {}, 0
        for bound, n in zip(self._bounds + (float("inf"),), counts):
            running += n
            out[format_le(bound)] = running
        return out, total, running

    def buckets(self) -> dict:
        """{le_label: cumulative count} including the implicit +Inf."""
        return self.exposition()[0]

    def snapshot(self) -> dict:
        snap = self._hist.snapshot()
        snap["buckets"] = self.buckets()
        return snap


class _NoopMetric:
    """Shared do-nothing metric for a disabled registry: every mutator is
    a no-op, every reader is empty/zero. One instance serves all names."""

    __slots__ = ()
    kind = "noop"

    def inc(self, n: float = 1):
        pass

    def set(self, v: float):
        pass

    def observe(self, v: float):
        pass

    def percentile(self, q: float) -> float:
        return 0.0

    @property
    def value(self) -> float:
        return 0.0

    def snapshot(self) -> dict:
        return {}


_NOOP_METRIC = _NoopMetric()


class MetricRegistry:
    """Get-or-create factory + exposition for named metrics.

    Identity is (name, sorted labels); re-registering the same identity
    returns the SAME object (callers can hold or re-look-up freely), and
    re-registering a name as a different metric type raises — a silent
    type flip would corrupt exposition.
    """

    def __init__(self, enabled: bool = True, histogram_window: int = 2048):
        self.enabled = enabled
        self._histogram_window = histogram_window
        self._lock = threading.Lock()
        # name -> (kind, help, {labels_key: metric})
        self._families: Dict[str, tuple] = {}

    def _get(self, cls, name: str, help_: str, labels: dict):
        if not self.enabled:
            return _NOOP_METRIC
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        bad = [k for k in labels if not _LABEL_RE.match(str(k))]
        if bad:
            raise ValueError(f"invalid label name(s) {bad} on {name!r}")
        key = _labels_key(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = (cls.kind, help_, {})
                self._families[name] = fam
            elif fam[0] != cls.kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam[0]}, "
                    f"requested {cls.kind}"
                )
            metric = fam[2].get(key)
            if metric is None:
                metric = (
                    cls(window=self._histogram_window)
                    if cls is Histogram else cls()
                )
                fam[2][key] = metric
            return metric

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "", **labels) -> Histogram:
        return self._get(Histogram, name, help, labels)

    # ------------------------------------------------------------- reading

    def collect(self) -> Dict[str, Tuple[str, Dict[LabelsKey, object]]]:
        """{name: (kind, {labels_key: metric})} — a consistent shallow
        copy for PROGRAMMATIC readers (the SLO engine matching selectors
        against counter series, the flight recorder diffing deltas).
        The metric objects are the live ones: read-only use."""
        with self._lock:
            return {
                n: (kind, dict(series))
                for n, (kind, _, series) in self._families.items()
            }

    def snapshot(self) -> dict:
        """JSON-ready dump: {"counters": {rendered_name: value}, "gauges":
        {...}, "histograms": {rendered_name: {count, p50, ...}}}."""
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        with self._lock:
            families = {
                n: (kind, dict(series))
                for n, (kind, _, series) in self._families.items()
            }
        for name, (kind, series) in sorted(families.items()):
            for key, metric in sorted(series.items()):
                rendered = name + render_labels(key)
                if kind == "histogram":
                    out["histograms"][rendered] = metric.snapshot()
                else:
                    out[kind + "s"][rendered] = metric.value
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition (v0.0.4). Histograms export as REAL
        histograms: cumulative `_bucket{le=...}` samples (+Inf included)
        plus `_sum`/`_count` — aggregatable across replicas and
        `histogram_quantile()`-able, unlike the summary-quantile gauges
        this used to emit."""
        lines = []
        with self._lock:
            families = {
                n: (kind, help_, dict(series))
                for n, (kind, help_, series) in self._families.items()
            }
        for name, (kind, help_, series) in sorted(families.items()):
            if help_:
                lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {kind}")
            for key, metric in sorted(series.items()):
                if kind == "histogram":
                    buckets, vsum, count = metric.exposition()
                    for le, cum in buckets.items():
                        bkey = tuple(sorted(key + (("le", le),)))
                        lines.append(
                            f"{name}_bucket{render_labels(bkey)} {cum}"
                        )
                    lines.append(f"{name}_sum{render_labels(key)} "
                                 f"{vsum}")
                    lines.append(f"{name}_count{render_labels(key)} "
                                 f"{count}")
                else:
                    lines.append(
                        f"{name}{render_labels(key)} {metric.value}"
                    )
        return "\n".join(lines) + ("\n" if lines else "")


_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>[^\s]+)\s*$"
)
_LABEL_PAIR_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"'
)


def parse_prometheus_text(text: str) -> Dict[Tuple[str, LabelsKey], float]:
    """Minimal Prometheus text-format parser: {(name, labels): value}.

    Enough of the grammar to round-trip `to_prometheus()` output (and any
    plain scrape of counters/gauges/histograms — cumulative
    `_bucket{le=...}` samples are ordinary samples whose `le` label keys
    the bound, `+Inf` included); not a validator. Raises ValueError on a
    line it cannot parse — a silently-skipped sample would make the
    round-trip test vacuous.
    """
    out: Dict[Tuple[str, LabelsKey], float] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"unparseable exposition line {lineno}: "
                             f"{line!r}")
        labels: LabelsKey = ()
        if m.group("labels"):
            labels = tuple(sorted(
                (k, _unescape(v))
                for k, v in _LABEL_PAIR_RE.findall(m.group("labels"))
            ))
        out[(m.group("name"), labels)] = float(m.group("value"))
    return out


#: shared disabled registry, the analog of trace.NULL_TRACER
NULL_REGISTRY = MetricRegistry(enabled=False)


def flatten_snapshot(snap: dict, prefix: str = "") -> Dict[str, float]:
    """Flatten any nested dict of numerics (a registry snapshot, an engine
    stats payload, a bench artifact) into {dotted.path: float} — the form
    the regression gate (telemetry/check.py) compares."""
    flat: Dict[str, float] = {}
    for k, v in snap.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            flat.update(flatten_snapshot(v, key))
        elif isinstance(v, bool):
            continue  # booleans are state, not measurements
        elif isinstance(v, (int, float)):
            flat[key] = float(v)
    return flat
