"""Span-based structured tracing: where the time goes, as data.

The repo's phases — harness step (data fetch / compute / metrics fetch /
checkpoint save), serving request lifecycle (enqueue -> batch -> compile
-> execute -> respond), reliability restart/recovery episodes — were
observable only through `print` timestamps. A `Tracer` turns each phase
into a nestable, thread-safe span with attributes, exportable as:

  * Chrome trace-event JSON (`export_chrome`): open in Perfetto
    (https://ui.perfetto.dev) or chrome://tracing — per-thread timelines
    with nesting rendered from same-tid ts/dur containment;
  * JSONL (`export_jsonl`): one span per line for ad-hoc analysis;
  * an in-process summary (`summary()`): per-span-name count / total /
    mean / max seconds, the payload `ServingEngine.stats()` embeds.

Cost contract: a DISABLED tracer is near-zero-cost — `span()` returns a
shared no-op singleton (no allocation, no lock, no record), so
instrumentation can stay in production code paths unconditionally. Use
the module-level `NULL_TRACER` as the default wiring value.

Memory is bounded: at most `max_spans` completed spans are retained;
further spans are counted in `dropped` (reported in `summary()` and the
Chrome export) rather than silently discarded — truncated data must
never read as complete data.

Trace correlation: `new_trace_id()` mints a request-scoped id at the
serving front door (fleet/engine `submit()`); `Tracer.bind_trace(id)`
binds it thread-locally so every span recorded on that thread while
bound carries a `trace_id` attribute, and multi-request phases (a batch,
a device dispatch) attach the explicit `trace_ids` list instead. The
same id travels queueing, dispatcher routing, requeues onto OTHER
replicas, and the response (`PredictionResult.trace_id`), so one grep
over an export reconstructs a request's whole cross-thread,
cross-replica life (docs/OBSERVABILITY.md "The operations plane").
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
import uuid
from typing import Optional


def new_trace_id() -> str:
    """A fresh 16-hex-char request trace id (random, not time-derived:
    two fleets started in the same instant must not collide)."""
    return uuid.uuid4().hex[:16]


class _NullSpan:
    """Shared no-op span: the disabled-tracer fast path. Stateless and
    reentrant, so ONE module-level instance serves every call site."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, key, value):  # noqa: ARG002 — signature parity with _Span
        return self


_NULL_SPAN = _NullSpan()


class _Span:
    """One live span; created by `Tracer.span` and recorded on exit."""

    __slots__ = ("_tracer", "name", "cat", "attrs", "_t0", "_depth")

    def __init__(self, tracer: "Tracer", name: str, cat: str, attrs: dict):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.attrs = attrs

    def set(self, key, value):
        """Attach/overwrite one attribute mid-span."""
        self.attrs[key] = value
        return self

    def __enter__(self):
        self._depth = self._tracer._push()
        self._t0 = self._tracer._clock()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = self._tracer._clock() - self._t0
        self._tracer._pop()
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        self._tracer._record(
            self.name, self.cat, self._t0, dur, self._depth, self.attrs
        )
        return False


class Tracer:
    """Thread-safe collector of completed spans.

    Args:
      enabled: False gives the no-op fast path (see module docstring).
      max_spans: retention bound; overflow increments `dropped`.
      clock: injectable monotonic clock (tests pin time).
    """

    def __init__(self, enabled: bool = True, max_spans: int = 100_000,
                 clock=time.perf_counter):
        if max_spans <= 0:
            raise ValueError(f"max_spans must be positive, got {max_spans}")
        self.enabled = enabled
        self.max_spans = max_spans
        self._clock = clock
        self._t_origin = clock()
        self._lock = threading.Lock()
        self._spans: list = []
        self.dropped = 0
        self._tls = threading.local()

    # ------------------------------------------------------------ recording

    def span(self, name: str, cat: str = "app", **attrs):
        """Context manager for one timed phase; attributes are JSON leaves.

        ``with tracer.span("serving.batch", cat="serving", bucket=64):``
        """
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, cat, attrs)

    def add(self, name: str, duration_s: float, cat: str = "app",
            end_at: Optional[float] = None, **attrs):
        """Record a span measured elsewhere (e.g. queue wait computed from
        a request's submit timestamp): ends at `end_at` (default: now) on
        this tracer's clock, started `duration_s` earlier."""
        if not self.enabled:
            return
        end = self._clock() if end_at is None else end_at
        self._record(name, cat, end - duration_s, duration_s, 0, attrs)

    @contextlib.contextmanager
    def bind_trace(self, trace):
        """Bind a trace identity to the CURRENT thread for the enclosed
        block: every span recorded here (nested spans included, helpers
        that never heard of tracing included — the AOT compile inside a
        device dispatch is the motivating case) inherits it unless the
        span set its own. `trace` is one id (str; spans gain `trace_id`)
        or a list of ids for batch-scoped work (spans gain `trace_ids`).
        No-op (beyond one boolean test) on a disabled tracer."""
        if not self.enabled or not trace:
            yield
            return
        prev = getattr(self._tls, "trace", None)
        self._tls.trace = trace
        try:
            yield
        finally:
            self._tls.trace = prev

    def current_trace_id(self) -> Optional[str]:
        """The single id bound to this thread, if any (None under a
        list binding — a batch has no one id)."""
        bound = getattr(self._tls, "trace", None)
        return bound if isinstance(bound, str) else None

    def _push(self) -> int:
        depth = getattr(self._tls, "depth", 0)
        self._tls.depth = depth + 1
        return depth

    def _pop(self):
        self._tls.depth = getattr(self._tls, "depth", 1) - 1

    def _record(self, name, cat, t0, dur, depth, attrs):
        bound = getattr(self._tls, "trace", None)
        if isinstance(bound, str):
            if "trace_id" not in attrs:
                attrs["trace_id"] = bound
        elif bound and "trace_ids" not in attrs:
            attrs["trace_ids"] = list(bound)
        rec = {
            "name": name,
            "cat": cat,
            "ts_s": t0 - self._t_origin,
            "dur_s": dur,
            "depth": depth,
            "tid": threading.get_ident(),
            "thread": threading.current_thread().name,
            "attrs": attrs,
        }
        with self._lock:
            if len(self._spans) >= self.max_spans:
                self.dropped += 1
            else:
                self._spans.append(rec)

    # ------------------------------------------------------------- reading

    def spans(self, last: Optional[int] = None) -> list:
        """Snapshot (shallow copies) of the completed spans; `last=N`
        copies only the N most recent (the flight recorder's bundle
        tail — copying 100k spans per incident would be the outage
        amplifying itself)."""
        with self._lock:
            if last is None:
                src = self._spans
            else:
                # [-last:] with last=0 is the WHOLE list, not none of it
                src = self._spans[-last:] if last > 0 else []
            return [dict(s) for s in src]

    @property
    def span_count(self) -> int:
        """Retained-span count without copying the records."""
        with self._lock:
            return len(self._spans)

    def summary(self) -> dict:
        """Per-span-name aggregate: {name: {count, total_s, mean_s, max_s}}
        plus a `dropped` count when retention overflowed."""
        agg: dict = {}
        with self._lock:
            spans = list(self._spans)
            dropped = self.dropped
        for s in spans:
            a = agg.setdefault(
                s["name"], {"count": 0, "total_s": 0.0, "max_s": 0.0}
            )
            a["count"] += 1
            a["total_s"] += s["dur_s"]
            if s["dur_s"] > a["max_s"]:
                a["max_s"] = s["dur_s"]
        for a in agg.values():
            a["mean_s"] = a["total_s"] / a["count"]
            a["total_s"] = round(a["total_s"], 6)
            a["mean_s"] = round(a["mean_s"], 6)
            a["max_s"] = round(a["max_s"], 6)
        if dropped:
            agg["_dropped"] = dropped
        return agg

    # ------------------------------------------------------------ exporters

    def chrome_trace(self) -> dict:
        """Chrome trace-event JSON object format: complete ("ph": "X")
        events in microseconds, one per span, plus thread-name metadata so
        Perfetto labels the worker/client timelines. Nesting needs no
        parent links — same-tid ts/dur containment renders the stack."""
        with self._lock:
            spans = list(self._spans)
            dropped = self.dropped
        events = []
        threads_seen = {}
        for s in spans:
            tid = s["tid"]
            if tid not in threads_seen:
                threads_seen[tid] = s["thread"]
                events.append({
                    "name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
                    "args": {"name": s["thread"]},
                })
            events.append({
                "name": s["name"],
                "cat": s["cat"],
                "ph": "X",
                # clamp: a retro-recorded span (Tracer.add) can nominally
                # start before the tracer existed; viewers expect ts >= 0
                "ts": round(max(0.0, s["ts_s"]) * 1e6, 3),
                "dur": round(s["dur_s"] * 1e6, 3),
                "pid": 1,
                "tid": tid,
                "args": {**s["attrs"], "depth": s["depth"]},
            })
        out = {"traceEvents": events, "displayTimeUnit": "ms"}
        if dropped:
            out["otherData"] = {"dropped_spans": dropped}
        return out

    def export_chrome(self, path: str):
        """Write the Chrome trace-event JSON; open in Perfetto or
        chrome://tracing (docs/OBSERVABILITY.md)."""
        with open(path, "w") as fh:
            json.dump(self.chrome_trace(), fh)

    def export_jsonl(self, path: str):
        """One span record per line (append mode: successive phases of one
        run accumulate into one stream)."""
        with open(path, "a") as fh:
            for s in self.spans():
                fh.write(json.dumps(s) + "\n")


#: shared disabled tracer — the default for every instrumented call site,
#: so production paths pay one `if not enabled` per span and nothing else
NULL_TRACER = Tracer(enabled=False, max_spans=1)
