"""Serving cost & profiling plane: per-executable chip-cost accounting.

PR 12 (telemetry/goodput.py) gave TRAINING a full wall-clock economy —
every second classified, goodput/badput ratios, analytic-FLOPs MFU. The
serving tier, the thing the ROADMAP north star says must carry heavy
traffic, could until this module answer "how many requests completed?"
but not "what does one request COST in chip-seconds, and how much
capacity is left?" — which is exactly the question the PR 11 autoscaler
needs a model for (it steered on queue symptoms), and the question
ParaFold (arxiv 2111.06340) / ScaleFold (arxiv 2404.11068) answer first
before optimizing anything. Three cooperating pieces:

`ExecutableCostLedger` — one row ("cell") per distinct serving
executable the fleet runs, keyed (pool, bucket, schedule, backend_arm,
weight_dtype). Each cell JOINS three columns:

  * analytic — forward matmul FLOPs per request (`utils/flops.py
    model_fwd_flops` at the bucket's padded shape; known at engine
    build, zero measurement needed);
  * priced   — per-chip residency bytes (`serving/sp_arm.py
    schedule_residency`: eval_shape structs, the same pricing the SP
    planner uses — the int8/SP cells price their real trees);
  * measured — EMA of device-seconds and real-requests per dispatched
    batch (compile time EXCLUDED: the engine subtracts the compile
    tracker's delta, so a bucket's first batch does not poison its EMA).

and derives `serve_chip_seconds_per_request` (EMA batch device-seconds x
chips / EMA batch requests — the per-request price in chip time) and a
serving-MFU gauge (achieved FLOP/s per chip vs a declared peak, same
honest-absence contract as the training ledger: no declared peak, no
MFU). The analytic column doubles as the per-bucket serving-forward
FLOP gauges (`serve_forward_flops`) the training-only `flops_gauges`
never covered. `pool_rate_rps` turns the measured columns into a
per-replica service-rate model — the capacity half of the fleet's
`fleet_pool_headroom_ratio` (serving/fleet.py `sample_gauges` supplies
the arrival half and the autoscaler's new headroom up-trigger consumes
the ratio).

`ServeGoodputLedger` — the serving twin of `GoodputLedger`: every
replica-second classified into execute / compile / probe / drain /
requeue / idle, with idle the explicit remainder so the buckets sum to
the replica's wall clock BY CONSTRUCTION. Accounting is delta-based
(`add`), not stack-based like the training ledger: serving time is
accounted from several threads (engine worker, watchdog runner, health
thread), and cross-thread exclusive stacks cannot compose — instead the
engine subtracts the nested compile delta explicitly, and the health
probe (`probe_span`) subtracts whatever the engine accounted during the
probe's round trip, so overlap between concurrent accounters stays
within the documented <=1% of wall (the chaos test pins it). "requeue"
is the device time burned by a FAILED dispatch — work whose requests
then requeue onto another replica or fail; it is the fleet's failover
bill, separated from productive execute.

`FlightBook` — exemplar flight records: a bounded ring of full
per-request flight paths (trace_id, pool, replica, bucket, schedule,
arm, queue wait, requeue/cache provenance, every lifecycle event),
queryable by trace_id at the ops plane's `/explainz?trace_id=` endpoint
— "explain this request" end to end across the featurize tier,
admission, and every replica it touched. Latency histograms tell you
the p99 moved; the flight book tells you what the p99 REQUEST did.

docs/OBSERVABILITY.md "The serving cost plane" is the operator guide;
docs/OPERATIONS.md maps headroom-low / serve-goodput-drop /
badput-by-cause to first diagnostics.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import threading
import time
from typing import Dict, Optional, Tuple

from alphafold2_tpu.telemetry.registry import (
    NULL_REGISTRY,
    MetricRegistry,
)

# --- the executable cost ledger ----------------------------------------------

#: cell key: one row per distinct serving executable the fleet runs
CellKey = Tuple[str, int, str, str, str]  # (pool, bucket, schedule, arm, dtype)


@dataclasses.dataclass
class CostCell:
    """One (pool, bucket, schedule, backend_arm, weight_dtype) row."""

    pool: str
    bucket: int
    schedule: str          # dense / sp_msa / sp_seq (the SP plan's choice)
    backend_arm: str       # resolved kernel arm (ops/dispatch.py)
    weight_dtype: str      # f32 / int8 (the precision arm)
    # analytic + priced columns (known at engine build, chip-free):
    forward_flops: float = 0.0     # matmul FLOPs of ONE request's forward
    residency_bytes: int = 0       # per-chip priced residency (sp_arm)
    chips: int = 1                 # devices one executable occupies
    max_batch: int = 1             # the executable's batch dimension
    # measured columns (EMA over dispatched batches, compile excluded):
    batches: int = 0
    requests: int = 0
    device_seconds: float = 0.0    # cumulative execute wall (x1, not xchips)
    ema_batch_seconds: Optional[float] = None
    ema_batch_requests: Optional[float] = None

    @property
    def key(self) -> CellKey:
        return (self.pool, self.bucket, self.schedule, self.backend_arm,
                self.weight_dtype)

    # ---------------------------------------------------------- derived

    def chip_seconds_per_request(self) -> Optional[float]:
        """The headline number: chip-seconds one request of this cell
        costs (EMA batch device-seconds x chips / EMA batch requests).
        None until a batch has been measured — an unmeasured cell must
        never read as a free one."""
        if not self.ema_batch_seconds or not self.ema_batch_requests:
            return None
        return self.ema_batch_seconds * self.chips / self.ema_batch_requests

    def flops_per_sec_per_chip(self) -> Optional[float]:
        """Achieved analytic FLOP/s per chip while this executable runs."""
        if not self.ema_batch_seconds or not self.ema_batch_requests:
            return None
        return (self.ema_batch_requests * self.forward_flops
                / (self.ema_batch_seconds * self.chips))

    def mfu(self, peak_flops: Optional[float]) -> Optional[float]:
        achieved = self.flops_per_sec_per_chip()
        if achieved is None or not peak_flops:
            return None
        return achieved / peak_flops

    def as_dict(self, peak_flops: Optional[float] = None) -> dict:
        out = {
            "pool": self.pool,
            "bucket": self.bucket,
            "schedule": self.schedule,
            "backend_arm": self.backend_arm,
            "weight_dtype": self.weight_dtype,
            "forward_flops": self.forward_flops,
            "residency_bytes": int(self.residency_bytes),
            "chips": self.chips,
            "max_batch": self.max_batch,
            "batches": self.batches,
            "requests": self.requests,
            "device_seconds": self.device_seconds,
            "ema_batch_seconds": self.ema_batch_seconds,
            "ema_batch_requests": self.ema_batch_requests,
            "chip_seconds_per_request": self.chip_seconds_per_request(),
            "flops_per_sec_per_chip": self.flops_per_sec_per_chip(),
        }
        m = self.mfu(peak_flops)
        if m is not None:
            out["mfu"] = m
        return out


class ExecutableCostLedger:
    """Per-executable chip-cost rows (module docstring).

    Shared fleet-wide: every replica of a pool observes into the SAME
    cell, so the EMA is the pool's price, not one replica's. Writers are
    the engine worker threads (`observe_batch`); readers are the ops
    plane (`publish`/`snapshot`) and the fleet's headroom math
    (`pool_rate_rps`) — the lock covers that split.
    """

    _EMA_ALPHA = 0.25

    def __init__(self, registry: MetricRegistry = NULL_REGISTRY):
        self.registry = registry
        self._lock = threading.Lock()
        self._cells: Dict[CellKey, CostCell] = {}
        self._peak: Optional[float] = None
        self._published_requests: Dict[CellKey, int] = {}

    def set_peak(self, peak_flops: Optional[float]):
        """Declare the per-chip peak FLOP/s for the serving-MFU column
        (None = publish achieved FLOP/s only, the training ledger's
        honest-absence contract)."""
        with self._lock:
            self._peak = float(peak_flops) if peak_flops else None

    def register_cell(self, *, pool: str, bucket: int, schedule: str,
                      backend_arm: str, weight_dtype: str,
                      forward_flops: float, residency_bytes: int,
                      chips: int = 1, max_batch: int = 1) -> CellKey:
        """Create (or refresh the analytic columns of) one cell —
        idempotent: N replicas of a pool register the same cell once
        each and share its measured columns."""
        key = (str(pool), int(bucket), str(schedule), str(backend_arm),
               str(weight_dtype))
        with self._lock:
            cell = self._cells.get(key)
            if cell is None:
                cell = CostCell(pool=key[0], bucket=key[1], schedule=key[2],
                                backend_arm=key[3], weight_dtype=key[4])
                self._cells[key] = cell
            cell.forward_flops = float(forward_flops)
            cell.residency_bytes = int(residency_bytes)
            cell.chips = max(1, int(chips))
            cell.max_batch = max(1, int(max_batch))
        return key

    def observe_batch(self, key: CellKey, *, device_seconds: float,
                      requests: int):
        """One dispatched batch of `requests` real requests that held the
        device for `device_seconds` (compile already excluded by the
        engine). Unknown keys auto-register a bare cell (a custom
        engine_factory that skipped registration must not lose its
        measurements)."""
        with self._lock:
            cell = self._cells.get(key)
            if cell is None:
                cell = CostCell(pool=key[0], bucket=key[1], schedule=key[2],
                                backend_arm=key[3], weight_dtype=key[4])
                self._cells[key] = cell
            cell.batches += 1
            cell.requests += int(requests)
            cell.device_seconds += float(device_seconds)
            a = self._EMA_ALPHA
            cell.ema_batch_seconds = (
                float(device_seconds) if cell.ema_batch_seconds is None
                else a * float(device_seconds)
                + (1 - a) * cell.ema_batch_seconds)
            cell.ema_batch_requests = (
                float(requests) if cell.ema_batch_requests is None
                else a * float(requests) + (1 - a) * cell.ema_batch_requests)

    # ------------------------------------------------------------- reading

    def cells(self) -> list:
        with self._lock:
            peak = self._peak
            rows = [dataclasses.replace(c) for c in self._cells.values()]
        return [c.as_dict(peak) for c in sorted(
            rows, key=lambda c: (c.pool, c.bucket, c.schedule))]

    def pool_rate_rps(self, pool: str) -> Optional[float]:
        """Per-REPLICA service rate model for one pool: requests served
        per device-busy second, over the pool's cumulative measured
        columns (an intensive quantity — N replicas contributing to one
        cell do not inflate it). None until something was measured: the
        headroom gauge must stay absent rather than divide by a guess."""
        with self._lock:
            secs = sum(c.device_seconds for c in self._cells.values()
                       if c.pool == pool)
            reqs = sum(c.requests for c in self._cells.values()
                       if c.pool == pool)
        if secs <= 0 or reqs <= 0:
            return None
        return reqs / secs

    def fleet_chip_seconds_total(self) -> float:
        """Cumulative CHIP-seconds spent executing, summed over every
        cell: `device_seconds` is x1 wall per batch, so each cell scales
        by its chip count. An extensive quantity — the numerator of the
        fleet-amortized `fleet_chip_seconds_per_request` gauge, which is
        what the artifact-store/coalescing tier (ISSUE 17) actually
        lowers: cache hits complete requests without adding here."""
        with self._lock:
            return sum(c.device_seconds * c.chips
                       for c in self._cells.values())

    def snapshot(self) -> dict:
        with self._lock:
            peak = self._peak
        return {"peak_flops_per_chip": peak, "cells": self.cells()}

    def publish(self):
        """Write every cell into the registry as labeled gauges (the
        `/metrics` view of the ledger): the analytic columns always, the
        measured/derived columns once a batch was observed. Volume rides
        a real counter (delta-published so it only ever grows)."""
        reg = self.registry
        with self._lock:
            peak = self._peak
            rows = [dataclasses.replace(c) for c in self._cells.values()]
        for cell in rows:
            labels = {
                "pool": cell.pool, "bucket": str(cell.bucket),
                "schedule": cell.schedule, "backend_arm": cell.backend_arm,
                "weight_dtype": cell.weight_dtype,
            }
            reg.gauge(
                "serve_forward_flops",
                help="analytic matmul FLOPs of one request's serving "
                     "forward at this cell's bucket (utils/flops.py)",
                **labels).set(cell.forward_flops)
            reg.gauge(
                "serve_residency_bytes",
                help="per-chip priced residency of this cell's executable "
                     "(serving/sp_arm.py eval_shape pricing)",
                **labels).set(cell.residency_bytes)
            with self._lock:
                seen = self._published_requests.get(cell.key, 0)
                delta = cell.requests - seen
                self._published_requests[cell.key] = cell.requests
            if delta > 0:
                reg.counter(
                    "serve_cell_requests_total",
                    help="requests served per cost-ledger cell",
                    **labels).inc(delta)
            csr = cell.chip_seconds_per_request()
            if csr is None:
                continue
            reg.gauge(
                "serve_chip_seconds_per_request",
                help="EMA chip-seconds one request of this cell costs "
                     "(batch device-seconds x chips / batch requests; "
                     "compile excluded)",
                **labels).set(csr)
            fps = cell.flops_per_sec_per_chip()
            if fps is not None:
                reg.gauge(
                    "serve_model_flops_per_sec",
                    help="achieved analytic FLOP/s per chip while this "
                         "cell's executable runs",
                    **labels).set(fps)
            m = cell.mfu(peak)
            if m is not None:
                reg.gauge(
                    "serve_mfu",
                    help="serving MFU: achieved / declared peak FLOP/s "
                         "per chip (--peak-tflops)",
                    **labels).set(m)


# --- the serving goodput ledger ----------------------------------------------

#: replica-second taxonomy. "idle" is never added directly — it is the
#: explicit remainder, so the causes sum to the replica's wall clock by
#: construction (cross-thread accounting overlap is bounded and pinned
#: <=1% by the chaos test; see module docstring).
SERVE_CAUSES = (
    "execute",   # successful device dispatch (the productive bucket)
    "compile",   # AOT executable compiles (build precompile + first call)
    "probe",     # health heartbeat round trips (minus their execute share)
    "drain",     # engine teardown during a health/retirement drain
    "requeue",   # device time burned by FAILED dispatches (failover bill)
    "idle",      # everything else: waiting for traffic
)

SERVE_GOODPUT_CAUSES = ("execute",)


class _ReplicaAccount:
    __slots__ = ("pool", "t0", "buckets")

    def __init__(self, pool: str, t0: float):
        self.pool = pool
        self.t0 = t0
        self.buckets: Dict[str, float] = {}


class ServeGoodputLedger:
    """Per-replica wall-clock economy for the serving tier (module
    docstring). Delta-based: accounters call `add(replica, cause,
    seconds)` from whatever thread measured the interval; `totals`
    derives idle as the remainder."""

    def __init__(self, registry: MetricRegistry = NULL_REGISTRY, *,
                 clock=time.monotonic):
        self.registry = registry
        self._clock = clock
        self._lock = threading.Lock()
        self._replicas: Dict[str, _ReplicaAccount] = {}

    def register(self, replica: str, pool: str = ""):
        """Start (or re-pool) a replica's clock. Idempotent: an engine
        restart behind the same replica name keeps the original wall
        origin — the drain gap shows up as drain + idle, not as a
        rewound clock."""
        if not replica:
            return
        with self._lock:
            acct = self._replicas.get(replica)
            if acct is None:
                self._replicas[replica] = _ReplicaAccount(pool, self._clock())
            elif pool:
                acct.pool = pool

    def add(self, replica: str, cause: str, seconds: float):
        if cause not in SERVE_CAUSES or cause == "idle":
            raise ValueError(
                f"unknown serve-goodput cause {cause!r}; expected one of "
                f"{SERVE_CAUSES[:-1]}")
        if not replica or seconds <= 0:
            return
        with self._lock:
            acct = self._replicas.get(replica)
            if acct is None:
                acct = _ReplicaAccount("", self._clock())
                self._replicas[replica] = acct
            acct.buckets[cause] = acct.buckets.get(cause, 0.0) + seconds

    def accounted(self, replica: str) -> float:
        with self._lock:
            acct = self._replicas.get(replica)
            return sum(acct.buckets.values()) if acct else 0.0

    @contextlib.contextmanager
    def probe_span(self, replica: str):
        """Account a health probe's round trip as "probe" — MINUS
        whatever the replica's engine accounted during it (the probe's
        own execute/compile runs on the worker thread and is already
        counted there; double-counting it would break sums-to-wall on
        every reinstatement probe, whose first dispatch compiles)."""
        t0 = self._clock()
        before = self.accounted(replica)
        try:
            yield
        finally:
            wall = self._clock() - t0
            inner = self.accounted(replica) - before
            self.add(replica, "probe", max(0.0, wall - inner))

    # ------------------------------------------------------------- reading

    def wall(self, replica: str) -> float:
        with self._lock:
            acct = self._replicas.get(replica)
            return self._clock() - acct.t0 if acct else 0.0

    def totals(self, replica: str) -> Dict[str, float]:
        """{cause: seconds} including the idle remainder (clamped at 0 —
        accounting overlap surfaces as sum > wall, which the chaos test
        bounds at 1%)."""
        with self._lock:
            acct = self._replicas.get(replica)
            if acct is None:
                return {}
            out = dict(acct.buckets)
            wall = self._clock() - acct.t0
        for cause in SERVE_CAUSES:
            out.setdefault(cause, 0.0)
        out["idle"] = max(0.0, wall - sum(
            v for k, v in out.items() if k != "idle"))
        return out

    def _replica_snapshot(self, replica: str) -> dict:
        # wall_s is the BUCKET SUM (the training ledger's snapshot
        # convention: every field of one snapshot derives from one
        # totals read, so ratio denominators are internally exact) —
        # an invariant CHECK must compare totals() against the live
        # wall() instead, or it compares a sum to itself
        totals = self.totals(replica)
        wall = sum(totals.values())
        with self._lock:
            pool = self._replicas[replica].pool
        productive = sum(totals.get(b, 0.0) for b in SERVE_GOODPUT_CAUSES)
        return {
            "pool": pool,
            "wall_s": wall,
            "buckets": totals,
            "goodput_ratio": productive / wall if wall > 0 else 0.0,
            "badput_s": {k: v for k, v in totals.items()
                         if k not in SERVE_GOODPUT_CAUSES},
        }

    def snapshot(self) -> dict:
        """JSON-ready dump: per replica and aggregated per pool."""
        with self._lock:
            names = list(self._replicas)
        replicas = {name: self._replica_snapshot(name) for name in names}
        pools: Dict[str, dict] = {}
        for snap in replicas.values():
            agg = pools.setdefault(
                snap["pool"] or "", {"wall_s": 0.0, "execute_s": 0.0})
            agg["wall_s"] += snap["wall_s"]
            agg["execute_s"] += snap["buckets"].get("execute", 0.0)
        for agg in pools.values():
            agg["goodput_ratio"] = (
                agg["execute_s"] / agg["wall_s"] if agg["wall_s"] > 0
                else 0.0)
        return {"replicas": replicas, "pools": pools}

    def publish(self):
        """Registry gauges: `serve_goodput_ratio{replica,pool}` +
        `serve_badput_seconds{replica,pool,cause}` +
        `serve_wall_seconds{replica,pool}` per replica, and the pool
        aggregate `serve_pool_goodput_ratio{pool}`."""
        reg = self.registry
        snap = self.snapshot()
        for name, rs in snap["replicas"].items():
            labels = {"replica": name, "pool": rs["pool"]}
            reg.gauge(
                "serve_wall_seconds",
                help="replica wall-clock seconds (serve-goodput ledger "
                     "lifetime)", **labels).set(rs["wall_s"])
            reg.gauge(
                "serve_goodput_ratio",
                help="productive execute seconds / replica wall seconds",
                **labels).set(rs["goodput_ratio"])
            for cause, s in rs["badput_s"].items():
                reg.gauge(
                    "serve_badput_seconds",
                    help="non-productive replica wall seconds by cause",
                    cause=cause, **labels).set(s)
        for pool, agg in snap["pools"].items():
            reg.gauge(
                "serve_pool_goodput_ratio",
                help="pool-aggregate execute seconds / wall seconds",
                pool=pool).set(agg["goodput_ratio"])


# --- exemplar flight records --------------------------------------------------


class FlightBook:
    """Bounded ring of per-request flight records, queryable by trace_id
    (the `/explainz` backing store; module docstring).

    A record is born at the serving front door (`begin`), accumulates
    lifecycle `events` (admitted, dispatch, requeue, ...), and is sealed
    with a terminal `finish` (outcome + provenance). Capacity evicts the
    OLDEST record wholesale — a truncated ring never shows a partial
    flight as a complete one. All methods are cheap (dict ops under one
    lock) and never raise on unknown ids: an evicted record's late event
    is dropped, not an error — observability must not outlive its
    budget."""

    def __init__(self, capacity: int = 512, *, clock=time.time):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._clock = clock
        self._lock = threading.Lock()
        self._records: "collections.OrderedDict[str, dict]" = (
            collections.OrderedDict())
        self._evicted = 0

    def begin(self, trace_id: str, **fields):
        if not trace_id:
            return
        with self._lock:
            rec = self._records.get(trace_id)
            if rec is not None:
                # a replayed id (client retry with the same trace_id):
                # keep one record, note the re-entry as an event
                rec["events"].append(
                    {"ts": self._clock(), "event": "resubmitted", **fields})
                return
            self._records[trace_id] = {
                "trace_id": trace_id,
                "ts": self._clock(),
                "outcome": None,
                "events": [{"ts": self._clock(), "event": "submitted",
                            **fields}],
                **fields,
            }
            while len(self._records) > self.capacity:
                self._records.popitem(last=False)
                self._evicted += 1

    def note(self, trace_id: str, event: str, **attrs):
        with self._lock:
            rec = self._records.get(trace_id)
            if rec is None:
                return
            rec["events"].append(
                {"ts": self._clock(), "event": event, **attrs})

    def finish(self, trace_id: str, outcome: str, **fields):
        with self._lock:
            rec = self._records.get(trace_id)
            if rec is None:
                return
            rec["outcome"] = outcome
            rec.update(fields)
            rec["events"].append(
                {"ts": self._clock(), "event": "terminal",
                 "outcome": outcome})

    # ------------------------------------------------------------- reading

    def get(self, trace_id: str) -> Optional[dict]:
        """Deep-enough copy of one flight (events list copied — a reader
        must never race the resolver's append)."""
        with self._lock:
            rec = self._records.get(trace_id)
            if rec is None:
                return None
            out = dict(rec)
            out["events"] = [dict(e) for e in rec["events"]]
            return out

    def recent(self, n: int = 20) -> list:
        """The most recent trace_ids (newest last) — `/explainz` without
        a trace_id lists these so an operator can find a flight to
        explain."""
        with self._lock:
            ids = list(self._records)
        return ids[-max(0, n):]

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "records": len(self._records),
                "capacity": self.capacity,
                "evicted": self._evicted,
            }
