"""Training observability plane: goodput ledger, pod federation, stragglers.

ScaleFold (arxiv 2404.11068) attributes its 10-hour AlphaFold training
to systematically finding data-stall and non-compute badput BEFORE
optimizing, and FastFold (arxiv 2203.00854) drives its parallelism
choices from per-phase time breakdowns. Until this module the trainers
had the opposite posture to serving: a proc-0-only metrics JSONL, no
live endpoint, no accounting of where wall clock goes, and no visibility
into which host of a pod is the straggler. Four cooperating pieces close
that gap:

`GoodputLedger` — classifies every wall-clock second of a training run
into named buckets (`BUCKETS`): data fetch, global-batch assembly,
compile, step execute, eval, checkpoint, restore, preemption drain, and
idle (the explicit remainder, so the buckets ALWAYS sum to wall clock —
the invariant the chaos matrix pins). Accounting is exclusive-time: a
nested `account()` (the pod path's batch assembly runs inside the step
dispatch) attributes to the inner bucket and subtracts from the outer.
Exposes lifetime goodput ratio (productive step seconds / wall),
badput-by-cause, per-step fetch/step histograms, and analytic
FLOPs-per-second / MFU (utils/flops.py numbers — XLA's own count is
scan-blind) as registry metrics, plus a progress watchdog
(`health(horizon)`: "down" when no step completed within the horizon —
the trainer `/healthz` 503).

`MetricFederation` — the pod-wide view. Each telemetry tick EVERY
process serializes {its Prometheus exposition, last step/fetch seconds}
and the payloads are allgathered (`compat.process_allgather`) so process
0 can serve one `/metrics` with a `process` label on every sample.
Ticks are COLLECTIVE: they must run from the training loop at the same
step on every process (never from the HTTP ticker thread — a background
collective would race the train step's own collectives).

`StragglerDetector` — consumes the federated per-process step/fetch
times: publishes cross-process skew gauges (max/median) and, when one
host's step time (-> `train_straggler`) or fetch time / local fetch
share (-> `train_data_stall`) diverges past a threshold for `patience`
consecutive observations, files a flight-recorder incident.

`TrainTelemetry` — the bundle the trainer loops actually thread through
(`run_resilient(..., telemetry=)`, both CLI plain loops): `account()`
passthrough, per-step bookkeeping, federation cadence, and the ops-plane
lifecycle. `build_train_telemetry` wires all of it from the shared
`add_observability_args` flag block (`--ops-port`, `--flight-dir`, ...).

docs/OBSERVABILITY.md "The training plane" is the operator guide;
docs/OPERATIONS.md maps `train_straggler` / `train_data_stall` to their
first diagnostic steps.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from alphafold2_tpu.telemetry.registry import (
    NULL_REGISTRY,
    MetricRegistry,
    parse_prometheus_text,
    render_labels,
)

#: the ledger's bucket taxonomy. "idle" is never accounted directly —
#: it is the explicit remainder (wall minus every accounted second), so
#: the buckets sum to wall clock BY CONSTRUCTION and a double-counting
#: bug shows up as negative idle (clamped, asserted in tests).
BUCKETS = (
    "data_fetch",   # host-side batch fetch/assembly (the data pipeline)
    "assembly",     # host-to-device / global-batch assembly (pod path)
    "compile",      # first-step jit trace+compile wall time
    "step",         # step dispatch + device execution (the productive bucket)
    "eval",         # held-out eval forward
    "checkpoint",   # checkpoint save/verify
    "restore",      # crash-recovery episodes (restart + restore)
    "preempt",      # preemption drain: final save before Preempted
    "idle",         # everything else (supervisor overhead, logging, gaps)
)

#: buckets counted as productive in the goodput ratio. Compile, eval and
#: checkpoints are overhead a perfect run amortizes to ~zero (ScaleFold
#: moves eval off the training stream for exactly this reason).
GOODPUT_BUCKETS = ("step",)


class GoodputLedger:
    """Wall-clock bucket accounting for one training run (module docstring).

    Accounting calls (`account`, `step_complete`) belong to the training
    loop thread; readers (`snapshot`, `health`, the registry gauges) may
    run on the ops-plane HTTP/ticker threads — the internal lock covers
    that split, not concurrent accounting from two threads.

    Args:
      registry: metric sink (`NULL_REGISTRY` = totals only, no metrics).
      clock: injectable monotonic clock (tests drive time explicitly).
      process_index: stamped into `snapshot()` for the federation payload.
    """

    def __init__(self, registry: MetricRegistry = NULL_REGISTRY, *,
                 clock: Callable[[], float] = time.perf_counter,
                 process_index: int = 0):
        self.registry = registry
        self.process_index = process_index
        self._clock = clock
        self._lock = threading.Lock()
        self._t0 = clock()
        self._buckets: Dict[str, float] = {
            b: 0.0 for b in BUCKETS if b != "idle"
        }
        self._stack: List[list] = []   # [bucket, t_enter, child_seconds]
        self._step_acc: Dict[str, float] = {}  # since last step_complete
        self._steps = 0
        self._compiled = False
        self._last_step_s = 0.0
        self._last_fetch_s = 0.0
        self._last_progress = self._t0
        self._step_flops: Optional[float] = None
        self._peak_flops: Optional[float] = None

    # ---------------------------------------------------------- accounting

    @contextlib.contextmanager
    def account(self, bucket: str):
        """Attribute the enclosed wall time to `bucket` (exclusive-time:
        a nested account claims its own seconds from the enclosing one)."""
        if bucket not in BUCKETS or bucket == "idle":
            raise ValueError(f"unknown ledger bucket {bucket!r}; "
                             f"expected one of {BUCKETS[:-1]}")
        frame = [bucket, self._clock(), 0.0]
        self._stack.append(frame)
        try:
            yield
        finally:
            now = self._clock()
            self._stack.pop()
            total = now - frame[1]
            self_dt = max(0.0, total - frame[2])
            with self._lock:
                self._buckets[bucket] += self_dt
                self._step_acc[bucket] = (
                    self._step_acc.get(bucket, 0.0) + self_dt
                )
            if self._stack:
                self._stack[-1][2] += total

    def step_bucket(self) -> str:
        """Bucket for the next step execution: "compile" until the first
        step completes (its wall time IS the jit trace+compile event),
        "step" after."""
        return "step" if self._compiled else "compile"

    def step_complete(self, step: int) -> Dict[str, float]:
        """One optimizer step finished: fold the per-step accumulation
        into histograms/gauges and reset the progress watchdog. Returns
        {"step_s", "fetch_s"} (this step's execute and data-fetch
        seconds) — the federation payload and the stall detector's
        input."""
        now = self._clock()
        with self._lock:
            acc, self._step_acc = self._step_acc, {}
            step_s = acc.get("step", 0.0) + acc.get("compile", 0.0)
            fetch_s = acc.get("data_fetch", 0.0)
            self._steps += 1
            self._compiled = True
            self._last_step_s = step_s
            self._last_fetch_s = fetch_s
            self._last_progress = now
        self.registry.counter(
            "train_steps_total", help="completed optimizer steps").inc()
        self.registry.histogram(
            "train_step_seconds",
            help="per-step execute wall seconds (compile included at "
                 "step 0)").observe(step_s)
        self.registry.histogram(
            "train_fetch_seconds",
            help="per-step host data-fetch wall seconds").observe(fetch_s)
        self.publish()
        return {"step_s": step_s, "fetch_s": fetch_s}

    def set_workload(self, step_flops: float,
                     peak_flops: Optional[float] = None):
        """Arm the MFU math: analytic FLOPs of one optimizer step
        (utils/flops.py train_step_flops) and, when known, the chip's
        peak FLOP/s (None = publish achieved FLOP/s only — an honest
        absence beats an MFU against a guessed peak)."""
        with self._lock:
            self._step_flops = float(step_flops)
            self._peak_flops = (
                float(peak_flops) if peak_flops else None
            )

    # ------------------------------------------------------------- reading

    @property
    def last_step_seconds(self) -> float:
        with self._lock:
            return self._last_step_s

    @property
    def last_fetch_seconds(self) -> float:
        with self._lock:
            return self._last_fetch_s

    def wall(self) -> float:
        return self._clock() - self._t0

    def totals(self) -> Dict[str, float]:
        """{bucket: seconds} including the idle remainder — sums to
        `wall()` by construction (idle clamps at 0, so an accounting
        overlap bug surfaces as sum > wall, which the tests assert
        against)."""
        with self._lock:
            out = dict(self._buckets)
        out["idle"] = max(0.0, self.wall() - sum(out.values()))
        return out

    def goodput_ratio(self) -> float:
        wall = self.wall()
        if wall <= 0:
            return 0.0
        totals = self.totals()
        return sum(totals[b] for b in GOODPUT_BUCKETS) / wall

    def badput(self) -> Dict[str, float]:
        """{cause: seconds} — every non-productive bucket, idle included."""
        return {b: s for b, s in self.totals().items()
                if b not in GOODPUT_BUCKETS}

    def flops_per_sec(self) -> Optional[float]:
        with self._lock:
            step_flops, steps = self._step_flops, self._steps
        wall = self.wall()
        if step_flops is None or wall <= 0:
            return None
        return steps * step_flops / wall

    def mfu(self) -> Optional[float]:
        achieved = self.flops_per_sec()
        with self._lock:
            peak = self._peak_flops
        if achieved is None or peak is None or peak <= 0:
            return None
        return achieved / peak

    def publish(self):
        """Write the ledger state into the registry (called on every
        step_complete and every ops tick — so, like snapshot(), it is
        built from ONE totals read: every gauge of a publish describes
        the same instant, and the per-step hot path takes the lock
        once, not seven times)."""
        reg = self.registry
        totals = self.totals()
        wall = sum(totals.values())
        with self._lock:
            steps = self._steps
            step_flops, peak = self._step_flops, self._peak_flops
        reg.gauge("train_wall_seconds",
                  help="run wall-clock seconds (ledger lifetime)"
                  ).set(wall)
        for bucket, s in totals.items():
            reg.gauge("train_bucket_seconds",
                      help="wall seconds by ledger bucket (sums to "
                           "train_wall_seconds)", bucket=bucket).set(s)
        productive = sum(totals[b] for b in GOODPUT_BUCKETS)
        reg.gauge("train_goodput_ratio",
                  help="productive step seconds / wall seconds"
                  ).set(productive / wall if wall > 0 else 0.0)
        for cause, s in totals.items():
            if cause in GOODPUT_BUCKETS:
                continue
            reg.gauge("train_badput_seconds",
                      help="non-productive wall seconds by cause",
                      cause=cause).set(s)
        if step_flops is not None and wall > 0:
            achieved = steps * step_flops / wall
            reg.gauge("train_model_flops_per_sec",
                      help="analytic achieved model FLOP/s "
                           "(utils/flops.py, steps x step_flops / wall)"
                      ).set(achieved)
            if peak:
                reg.gauge("train_mfu",
                          help="achieved / peak FLOP/s (requires a "
                               "declared peak)").set(achieved / peak)

    def snapshot(self) -> dict:
        """JSON-ready ledger dump (the trainer `/statusz` payload).
        Every field derives from ONE totals read: `wall_s` is the bucket
        sum and the ratio divides by that same sum, so the sums-to-wall
        invariant — and the ratio's denominator — hold EXACTLY within
        one snapshot (a live `wall()` read microseconds later would
        already disagree), and the hot callers (every /statusz request,
        every flight-recorder bundle) take the lock once, not seven
        times."""
        totals = self.totals()
        wall = sum(totals.values())
        with self._lock:
            steps = self._steps
            last_step_s, last_fetch_s = self._last_step_s, self._last_fetch_s
            step_flops, peak = self._step_flops, self._peak_flops
        out = {
            "process": self.process_index,
            "wall_s": wall,
            "buckets": totals,
            "goodput_ratio": (
                sum(totals[b] for b in GOODPUT_BUCKETS) / wall
                if wall > 0 else 0.0
            ),
            "badput_s": {b: s for b, s in totals.items()
                         if b not in GOODPUT_BUCKETS},
            "steps": steps,
            "last_step_s": last_step_s,
            "last_fetch_s": last_fetch_s,
        }
        if step_flops is not None and wall > 0:
            achieved = steps * step_flops / wall
            out["model_flops_per_sec"] = achieved
            if peak:
                out["mfu"] = achieved / peak
        return out

    def health(self, horizon_s: float = 600.0) -> dict:
        """Progress-watchdog liveness: "down" when no step completed
        within `horizon_s` (measured from ledger start before the first
        step, so a wedged first compile eventually pages too). The ops
        plane maps "down" to HTTP 503."""
        with self._lock:
            age = self._clock() - self._last_progress
            steps = self._steps
        stalled = age > horizon_s
        return {
            "status": "down" if stalled else "ok",
            "steps": steps,
            "last_step_age_s": age,
            "horizon_s": horizon_s,
        }


# --- pod-wide federation ------------------------------------------------------


def _allgather_bytes(payload: bytes) -> List[bytes]:
    """Every process's payload, via two `compat.process_allgather` calls
    (sizes first, then max-padded uint8 rows — payload lengths differ per
    process). COLLECTIVE: all processes must call with the same cadence.
    Single-process this degenerates to [payload]."""
    from alphafold2_tpu import compat

    data = np.frombuffer(payload, np.uint8)
    sizes = np.asarray(
        compat.process_allgather(np.asarray([data.size]), tiled=True)
    ).reshape(-1)
    padded = np.zeros((1, int(sizes.max())), np.uint8)
    padded[0, : data.size] = data
    rows = np.asarray(compat.process_allgather(padded, tiled=True))
    return [rows[i, : int(sizes[i])].tobytes() for i in range(len(sizes))]


def relabeled_exposition(text: str, **labels) -> str:
    """Re-emit a Prometheus text exposition with `labels` merged into
    every sample (comment lines dropped — the merged pod view is served
    untyped; `parse_prometheus_text` and real scrapers both accept it)."""
    samples = parse_prometheus_text(text)
    extra = tuple((k, str(v)) for k, v in labels.items())
    lines = []
    for (name, key) in sorted(samples):
        merged = tuple(sorted(dict(key + extra).items()))
        lines.append(f"{name}{render_labels(merged)} {samples[(name, key)]}")
    return "\n".join(lines) + ("\n" if lines else "")


class MetricFederation:
    """Allgathers per-process telemetry to every process each tick.

    `tick(step)` is a COLLECTIVE operation: every process of the pod must
    call it at the same training step (the trainer loops do, on the
    `every`-step cadence via `TrainTelemetry.step_complete`). The HTTP
    side only ever reads the last gathered state under a lock.
    """

    def __init__(self, registry: MetricRegistry, *,
                 ledger: Optional[GoodputLedger] = None,
                 process_index: Optional[int] = None,
                 every: int = 10,
                 gather_fn: Callable[[bytes], List[bytes]] = _allgather_bytes):
        if every < 1:
            raise ValueError(f"every must be >= 1, got {every}")
        if process_index is None:
            import jax

            process_index = jax.process_index()
        self.registry = registry
        self.ledger = ledger
        self.process_index = process_index
        self.every = every
        self._gather = gather_fn
        self._lock = threading.Lock()
        self._rows: List[dict] = []
        self._last_tick_step: Optional[int] = None

    def due(self, step: int) -> bool:
        return step % self.every == 0

    def tick(self, step: int) -> List[dict]:
        """Gather every process's payload; returns the decoded rows
        (sorted by process index). COLLECTIVE — see class docstring."""
        payload = {
            "process": self.process_index,
            "step": step,
            "prom": self.registry.to_prometheus(),
        }
        if self.ledger is not None:
            payload["step_s"] = self.ledger.last_step_seconds
            payload["fetch_s"] = self.ledger.last_fetch_seconds
            payload["goodput"] = self.ledger.goodput_ratio()
        rows = [json.loads(b.decode("utf-8"))
                for b in self._gather(json.dumps(payload).encode("utf-8"))]
        rows.sort(key=lambda r: r.get("process", 0))
        with self._lock:
            self._rows = rows
            self._last_tick_step = step
        return rows

    def rows(self) -> List[dict]:
        with self._lock:
            return list(self._rows)

    def remote_exposition(self) -> str:
        """The last-gathered samples of every OTHER process, each labeled
        with its `process` index (this process's samples are served live
        by `FederatedRegistryView`)."""
        parts = []
        for row in self.rows():
            if row.get("process") == self.process_index:
                continue
            parts.append(relabeled_exposition(
                row.get("prom", ""), process=row.get("process", "?")))
        return "".join(parts)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "processes": [r.get("process") for r in self._rows],
                "last_tick_step": self._last_tick_step,
                "every": self.every,
            }


class FederatedRegistryView:
    """Registry adapter for the trainer `OpsServer`: mutators and
    snapshots delegate to the LOCAL registry; `/metrics` exposition is
    the local samples (live, labeled `process=<self>`) plus every other
    process's last-federated samples — one scrape, whole pod."""

    def __init__(self, local: MetricRegistry, federation: MetricFederation):
        self._local = local
        self._federation = federation

    def counter(self, name, help="", **labels):
        return self._local.counter(name, help=help, **labels)

    def gauge(self, name, help="", **labels):
        return self._local.gauge(name, help=help, **labels)

    def histogram(self, name, help="", **labels):
        return self._local.histogram(name, help=help, **labels)

    def collect(self):
        return self._local.collect()

    def snapshot(self):
        return self._local.snapshot()

    def to_prometheus(self) -> str:
        own = relabeled_exposition(
            self._local.to_prometheus(),
            process=self._federation.process_index,
        )
        return own + self._federation.remote_exposition()


# --- straggler / data-stall detection ----------------------------------------


class StragglerDetector:
    """Fires flight-recorder incidents when training time diverges.

    Two failure shapes, each needing `patience` CONSECUTIVE bad
    observations (one slow garbage-collection pause must not page):

      * `train_straggler` — pod skew: one process's step time exceeds
        `skew_threshold` x the pod median (`observe_pod`, fed from the
        federation rows).
      * `train_data_stall` — the input pipeline is the bottleneck:
        locally, fetch time exceeds `stall_fraction` of the step's
        fetch+execute wall (`observe_local`); on a pod, one process's
        FETCH time exceeds the skew threshold vs the median
        (`observe_pod`).

    Sub-`min_seconds` medians/fetches never trigger (microsecond noise
    on tiny test models is not a straggler). Incidents fire ONCE per
    streak (re-armed when the signal recovers); `registry` gets the skew
    gauges and a stalled-steps counter.
    """

    def __init__(self, *, recorder=None,
                 registry: MetricRegistry = NULL_REGISTRY,
                 skew_threshold: float = 2.0, stall_fraction: float = 0.5,
                 patience: int = 3, min_seconds: float = 0.005):
        if skew_threshold <= 1.0:
            raise ValueError(
                f"skew_threshold must be > 1, got {skew_threshold}")
        if not 0.0 < stall_fraction < 1.0:
            raise ValueError(
                f"stall_fraction must be in (0, 1), got {stall_fraction}")
        if patience < 1:
            raise ValueError(f"patience must be >= 1, got {patience}")
        self.recorder = recorder
        self.registry = registry
        self.skew_threshold = skew_threshold
        self.stall_fraction = stall_fraction
        self.patience = patience
        self.min_seconds = min_seconds
        self._streaks: Dict[tuple, int] = {}

    def _observe(self, key: tuple, bad: bool, kind: str, **attrs):
        streak = self._streaks.get(key, 0) + 1 if bad else 0
        self._streaks[key] = streak
        if streak != self.patience:  # fire once per streak, at patience
            return
        self.registry.counter(
            "train_incidents_total",
            help="straggler/data-stall detections", kind=kind).inc()
        if self.recorder is not None:
            self.recorder.incident(
                kind, patience=self.patience, **attrs)

    def observe_local(self, step: int, *, fetch_s: float, step_s: float):
        """Single-process data-stall check on one completed step."""
        total = fetch_s + step_s
        bad = (fetch_s > self.min_seconds
               and total > 0
               and fetch_s / total > self.stall_fraction)
        self._observe(("local_stall",), bad, "train_data_stall",
                      step=step, fetch_s=fetch_s, step_s=step_s,
                      fetch_fraction=(fetch_s / total if total else 0.0))

    def observe_pod(self, step: int, rows: List[dict]):
        """Cross-process skew check on one federation tick. `rows` are
        the federation payloads ({"process", "step_s", "fetch_s"})."""
        if len(rows) < 2:
            return
        skew_help = "worst-process / median-process time this tick"
        step_skew = self.registry.gauge("train_step_time_skew",
                                        help=skew_help)
        fetch_skew = self.registry.gauge("train_fetch_time_skew",
                                         help=skew_help)
        for field, kind, gauge in (
            ("step_s", "train_straggler", step_skew),
            ("fetch_s", "train_data_stall", fetch_skew),
        ):
            vals = [(r.get("process", i), float(r.get(field, 0.0)))
                    for i, r in enumerate(rows)]
            times = sorted(v for _, v in vals)
            # LOWER median: on a 2-process pod the straggler must be
            # judged against its healthy peer, not against itself
            median = times[(len(times) - 1) // 2]
            worst_proc, worst = max(vals, key=lambda pv: pv[1])
            # significance rides the WORST time (a 0.2 s stall against a
            # near-zero healthy median IS a straggler), and the ratio's
            # denominator floors at min_seconds so it stays finite
            skew = worst / max(median, self.min_seconds)
            gauge.set(skew)
            bad = worst > self.min_seconds and skew > self.skew_threshold
            self._observe((kind, "pod"), bad, kind,
                          step=step, process=worst_proc, seconds=worst,
                          median_s=median, skew=skew, field=field)


# --- trainer wiring -----------------------------------------------------------


class TrainTelemetry:
    """The per-run observability bundle the trainer loops thread through.

    `enabled=False` (the NULL_TRAIN_TELEMETRY singleton) makes every
    hook a no-op — an uninstrumented run pays one boolean test per site,
    the same contract as NULL_TRACER/NULL_REGISTRY.
    """

    def __init__(self, *, ledger: Optional[GoodputLedger] = None,
                 federation: Optional[MetricFederation] = None,
                 detector: Optional[StragglerDetector] = None,
                 recorder=None, ops=None, logger=None,
                 enabled: bool = True):
        self.enabled = enabled
        self.ledger = ledger if ledger is not None else GoodputLedger()
        self.federation = federation
        self.detector = detector
        self.recorder = recorder
        self.ops = ops
        self.logger = logger

    def account(self, bucket: str):
        if not self.enabled:
            return contextlib.nullcontext()
        return self.ledger.account(bucket)

    def step_bucket(self) -> str:
        return self.ledger.step_bucket() if self.enabled else "step"

    def step_complete(self, step: int):
        """Per-step bookkeeping + the COLLECTIVE federation cadence: on a
        pod every process reaches this at the same step, so the gather
        inside stays in lockstep with the train step's own collectives."""
        if not self.enabled:
            return
        times = self.ledger.step_complete(step)
        if self.detector is not None:
            self.detector.observe_local(step, **times)
        if self.federation is not None and self.federation.due(step):
            rows = self.federation.tick(step)
            if (self.detector is not None
                    and self.federation.process_index == 0):
                self.detector.observe_pod(step, rows)

    def health(self, horizon_s: float = 600.0) -> dict:
        return self.ledger.health(horizon_s)

    def statusz(self) -> dict:
        # NO flight-recorder block here: this payload mounts as the ops
        # server's stats_fn, and OpsServer.statusz() already serves the
        # same recorder under its own top-level "flight_recorder" key —
        # embedding it twice would hand operators two copies to diverge
        out = {"goodput": self.ledger.snapshot()}
        if self.logger is not None and hasattr(self.logger, "tail"):
            out["loss_tail"] = self.logger.tail()
        if self.federation is not None:
            out["federation"] = self.federation.snapshot()
        return out

    def close(self):
        """Final publish + ops-plane shutdown (idempotent). Deliberately
        NO final federation tick: close() also runs on the crash/preempt
        paths, where a collective would hang the surviving processes."""
        if not self.enabled:
            return
        self.ledger.publish()
        if self.ops is not None:
            self.ops.stop()
            self.ops = None
        snap = self.ledger.snapshot()
        buckets = "  ".join(
            f"{b} {s:.1f}s" for b, s in sorted(snap["buckets"].items())
            if s > 0.05
        )
        print(f"goodput {snap['goodput_ratio']:.1%} over "
              f"{snap['wall_s']:.1f}s wall ({snap['steps']} steps): "
              f"{buckets}")


#: shared disabled bundle, the analog of NULL_TRACER / NULL_REGISTRY
NULL_TRAIN_TELEMETRY = TrainTelemetry(enabled=False)


def add_observability_args(ap):
    """The trainer live-observability argparse block shared by
    train_pre.py and train_end2end.py — one place to add the next knob."""
    ap.add_argument("--ops-port", type=int, default=None, metavar="PORT",
                    help="serve the live trainer ops plane on this port "
                         "(/metrics, /healthz progress watchdog, /statusz "
                         "goodput ledger + loss tail); 0 = ephemeral "
                         "(printed); unset = off. Pod runs offset a "
                         "fixed port by the process rank; process 0 "
                         "serves the federated pod view")
    ap.add_argument("--ops-port-file", default=None, metavar="PATH",
                    help="write the bound ops port here (for parent "
                         "processes driving --ops-port 0); on a pod only "
                         "process 0 — the federated view — writes it")
    ap.add_argument("--flight-dir", default=None, metavar="DIR",
                    help="arm the training flight recorder: straggler / "
                         "data-stall incidents snapshot forensic bundles "
                         "here")
    ap.add_argument("--progress-horizon-s", type=float, default=600.0,
                    help="/healthz turns 503 when no step completed "
                         "within this many seconds")
    ap.add_argument("--federate-every", type=int, default=10,
                    help="pod runs: allgather per-process telemetry to "
                         "process 0 every N steps (a collective — keep "
                         "modest)")
    ap.add_argument("--peak-tflops", type=float, default=None,
                    help="declared accelerator peak TFLOP/s for the "
                         "train_mfu gauge (unset = publish achieved "
                         "FLOP/s only)")


def observability_enabled(args) -> bool:
    """Whether the flags ask for the live plane (the trainers enable the
    metric registry when this OR tracing is on)."""
    return (getattr(args, "ops_port", None) is not None
            or getattr(args, "flight_dir", None) is not None)


def build_train_telemetry(args, *, registry: MetricRegistry,
                          tracer=None, logger=None,
                          step_flops: Optional[float] = None,
                          process_index: Optional[int] = None,
                          process_count: Optional[int] = None) -> TrainTelemetry:
    """Wire the full training observability plane from the shared flag
    block. Returns NULL_TRAIN_TELEMETRY when nothing was asked for and
    the registry is disabled (the zero-cost default path)."""
    from alphafold2_tpu.telemetry.ops_plane import FlightRecorder, OpsServer
    from alphafold2_tpu.telemetry.profiling import (
        device_memory_gauges,
        host_memory_gauges,
    )
    from alphafold2_tpu.telemetry.trace import NULL_TRACER

    if not observability_enabled(args) and not registry.enabled:
        return NULL_TRAIN_TELEMETRY
    if process_index is None or process_count is None:
        import jax

        process_index = jax.process_index()
        process_count = jax.process_count()

    tracer = tracer if tracer is not None else NULL_TRACER
    ledger = GoodputLedger(registry, process_index=process_index)
    if step_flops is not None:
        peak = getattr(args, "peak_tflops", None)
        ledger.set_workload(step_flops,
                            peak_flops=peak * 1e12 if peak else None)

    recorder = None
    if getattr(args, "flight_dir", None):
        flight_dir = args.flight_dir
        if process_count > 1:
            # per-process subdirectory: bundle names carry only a
            # per-process sequence number, so two processes writing the
            # same directory (shared storage is the normal pod setup)
            # would silently os.replace each other's forensic evidence
            import os

            flight_dir = os.path.join(flight_dir, f"p{process_index}")
        recorder = FlightRecorder(
            flight_dir, tracer=tracer, registry=registry,
            stats_fn=ledger.snapshot)
    detector = StragglerDetector(recorder=recorder, registry=registry)

    federation = None
    if process_count > 1:
        federation = MetricFederation(
            registry, ledger=ledger, process_index=process_index,
            every=getattr(args, "federate_every", 10))

    telemetry = TrainTelemetry(
        ledger=ledger, federation=federation, detector=detector,
        recorder=recorder, logger=logger)

    if getattr(args, "ops_port", None) is not None:
        view = (FederatedRegistryView(registry, federation)
                if federation is not None and process_index == 0
                else registry)
        horizon = getattr(args, "progress_horizon_s", 600.0)
        # pods: every process mounts its own local plane. A FIXED port
        # offsets by rank (co-hosted processes — the CPU-pod test
        # topology — would otherwise all bind the same socket and every
        # process after the first would die at construction); port 0
        # stays ephemeral everywhere.
        port = args.ops_port
        if port and process_count > 1:
            port += process_index
        ops = OpsServer(
            registry=view,
            health_fn=lambda: telemetry.health(horizon),
            stats_fn=telemetry.statusz,
            tracer=tracer, recorder=recorder,
            port=port,
        )
        # the ticker thread samples host/device memory between steps —
        # training runs were blind to RSS/HBM growth between checkpoints
        ops.add_tick(lambda: host_memory_gauges(registry))
        ops.add_tick(lambda: device_memory_gauges(registry))
        ops.add_tick(ledger.publish)
        ops.start()
        print(f"trainer ops plane on {ops.url} "
              f"(/metrics /healthz /statusz)")
        if getattr(args, "ops_port_file", None) and process_index == 0:
            # process 0 only: its plane serves the FEDERATED pod view,
            # and a shared filesystem must not race N writers onto one
            # path (last writer would win with a local-only port)
            import os

            tmp = args.ops_port_file + ".tmp"
            with open(tmp, "w") as fh:
                fh.write(str(ops.port))
            os.replace(tmp, args.ops_port_file)  # readers never see ""
        telemetry.ops = ops
    return telemetry
