"""alphafold2_tpu — a TPU-native (JAX/XLA/Pallas/pjit) protein structure framework.

Re-designed from scratch with the capabilities of alphafold2-pytorch v0.0.28
(the lucidrains / Eric Alcaide speculative AlphaFold2 reimplementation):
MSA + sequence dual-track axial-attention trunk -> distogram head ->
classical-geometry 3D realization (MDS + mirror fix) -> equivariant refinement.

The compute path is pure JAX (jit / pjit / shard_map / Pallas); parallelism is
expressed over a `jax.sharding.Mesh` with XLA collectives rather than NCCL.
"""

from alphafold2_tpu.constants import (
    MAX_NUM_MSA,
    NUM_AMINO_ACIDS,
    NUM_EMBEDDS_TR,
    DISTOGRAM_BUCKETS,
)

__version__ = "0.1.0"


_MODEL_EXPORTS = ("Alphafold2Config", "alphafold2_init", "alphafold2_apply")


def __getattr__(name):
    # lazy import so geometry-only use doesn't pull in the model stack
    if name in _MODEL_EXPORTS:
        from alphafold2_tpu import models

        return getattr(models, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "Alphafold2Config",
    "alphafold2_init",
    "alphafold2_apply",
    "MAX_NUM_MSA",
    "NUM_AMINO_ACIDS",
    "NUM_EMBEDDS_TR",
    "DISTOGRAM_BUCKETS",
]
