"""Kabsch optimal alignment.

Parity: reference `alphafold2_pytorch/utils.py:514-558` (`kabsch_torch`).
SVD-based optimal rotation of X onto Y with determinant sign correction.

TPU notes: the SVD runs on a 3x3 covariance (XLA custom-call, negligible
cost); like the reference (`utils.py:524` SVD on a detached matrix) the
rotation itself is treated as a constant w.r.t. gradients via stop_gradient,
so losses backprop through the *aligned coordinates*, not through the SVD.
The reference's per-structure Python `if d:` sign flip (`utils.py:527-529`)
becomes a batched `jnp.where`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def kabsch(X, Y, weights=None):
    """Align X onto Y. X, Y: (..., 3, N). Returns (X_aligned, Y_centered).

    `weights` (..., N), optional: per-point weights (e.g. a boolean atom
    mask) applied to the centroid and covariance. The reference selects
    valid atoms by boolean indexing before calling Kabsch
    (train_end2end.py:172) — dynamic shapes that cannot jit; a weighted
    Kabsch is the static-shape equivalent (zero-weight points do not
    influence the alignment but are still carried through the rotation).
    """
    X = jnp.asarray(X)
    Y = jnp.asarray(Y)
    squeeze = X.ndim == 2
    if squeeze:
        X, Y = X[None], Y[None]
        if weights is not None:
            weights = jnp.asarray(weights)[None]

    if weights is None:
        Xc = X - X.mean(axis=-1, keepdims=True)
        Yc = Y - Y.mean(axis=-1, keepdims=True)
        C = jnp.einsum("...dn,...en->...de", Xc, Yc)
    else:
        w = jnp.asarray(weights, X.dtype)[..., None, :]  # (..., 1, N)
        denom = jnp.maximum(jnp.sum(w, axis=-1, keepdims=True), 1e-8)
        Xc = X - jnp.sum(X * w, axis=-1, keepdims=True) / denom
        Yc = Y - jnp.sum(Y * w, axis=-1, keepdims=True) / denom
        # weight one side of the covariance only; Xc/Yc stay unweighted for
        # the returned aligned coords
        C = jnp.einsum("...dn,...en->...de", Xc * w, Yc)
    U, S, Vt = jnp.linalg.svd(jax.lax.stop_gradient(C))

    # reflection fix: flip the last singular direction where det < 0
    d = jnp.linalg.det(U) * jnp.linalg.det(Vt)
    flip = (d < 0.0)[..., None]
    U = U.at[..., :, -1].set(jnp.where(flip, -U[..., :, -1], U[..., :, -1]))

    # rotation taking X onto Y (torch convention C = V S W^T -> R = V W^T,
    # numpy convention C = U S Vt -> R = U @ Vt)
    R = jnp.einsum("...ij,...jk->...ik", U, Vt)
    X_aligned = jnp.einsum("...ji,...jn->...in", R, Xc)

    if squeeze:
        return X_aligned[0], Yc[0]
    return X_aligned, Yc


def Kabsch(A, B):
    """Public wrapper, reference `utils.py:698-711`."""
    return kabsch(A, B)
