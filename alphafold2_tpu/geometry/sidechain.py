"""NeRF point placement and backbone -> dense atom-cloud lifting.

Parity: reference `alphafold2_pytorch/utils.py:191-254` (`nerf_torch`,
`sidechain_container`). The reference places carbonyl oxygens with a Python
loop over residues and structures (`utils.py:240-253`); here the psi
dihedrals and NeRF extension are computed for all residues at once.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from alphafold2_tpu.constants import (
    BOND_ANG_CA_C_O,
    BOND_LEN_C_O,
    GLOBAL_PAD_CHAR,
    NUM_COORDS_PER_RES,
)
from alphafold2_tpu.geometry.dihedral import get_dihedral


def nerf(a, b, c, l, theta, chi):
    """Natural extension of reference frame: place point d after (a, b, c).

    Args:
      a, b, c: (..., 3) the three previous points; d bonds to c.
      l: (...,) bond length c-d.
      theta: (...,) bond angle b-c-d, radians in [-pi, pi].
      chi: (...,) dihedral between planes (a,b,c) and (b,c,d).

    Returns: d (..., 3).
    """
    a, b, c = map(jnp.asarray, (a, b, c))
    l = jnp.asarray(l)[..., None]
    theta = jnp.asarray(theta)[..., None]
    chi = jnp.asarray(chi)[..., None]

    ba = b - a
    cb = c - b
    n_plane = jnp.cross(ba, cb)
    n_plane_ = jnp.cross(n_plane, cb)
    # rotation with columns (cb, n_plane_, n_plane), each normalized
    rotate = jnp.stack([cb, n_plane_, n_plane], axis=-1)
    rotate = rotate / jnp.linalg.norm(rotate, axis=-2, keepdims=True)

    d_local = jnp.concatenate(
        [
            -jnp.cos(theta),
            jnp.sin(theta) * jnp.cos(chi),
            jnp.sin(theta) * jnp.sin(chi),
        ],
        axis=-1,
    )
    return c + l * jnp.einsum("...ij,...j->...i", rotate, d_local)


def sidechain_container(
    backbones,
    place_oxygen: bool = False,
    n_atoms: int = NUM_COORDS_PER_RES,
    padding: float = GLOBAL_PAD_CHAR,
):
    """Lift a backbone trace to a dense (batch, L, n_atoms, 3) cloud.

    Atom slots 0..2 get the (N, C-alpha, C) backbone; remaining slots are
    parked at the C-alpha position as a differentiable placeholder for a
    downstream refiner. If `place_oxygen`, slot 3 receives the carbonyl O
    built by NeRF opposite the psi dihedral (reference `utils.py:240-253`,
    vectorized; the final residue, which has no psi, uses 5*pi/4 as in
    `utils.py:243`).

    Args:
      backbones: (batch, L*3, 3) coordinates ordered (N, CA, C) per residue.

    Returns: (batch, L, n_atoms, 3).
    """
    backbones = jnp.asarray(backbones)
    batch, flat, _ = backbones.shape
    length = flat // 3
    bb = backbones.reshape(batch, length, 3, 3)

    # remaining slots parked at backbone atom index 2 — matching the
    # reference's actual behavior (utils.py:236 copies slot 2; its comment
    # says "c_alpha" but slot 2 is the carbonyl C in N/CA/C order)
    park = bb[:, :, 2]
    rest = jnp.broadcast_to(park[:, :, None, :], (batch, length, n_atoms - 3, 3))
    cloud = jnp.concatenate([bb, rest], axis=2)

    if place_oxygen:
        # psi_i = dihedral(N_i, CA_i, C_i, N_{i+1}); last residue has none
        n_next = bb[:, 1:, 0]
        psis = get_dihedral(bb[:, :-1, 0], bb[:, :-1, 1], bb[:, :-1, 2], n_next)
        psis = jnp.concatenate(
            [psis, jnp.full((batch, 1), np.pi * 5 / 4, backbones.dtype)], axis=1
        )
        oxy = nerf(
            bb[:, :, 0],
            bb[:, :, 1],
            bb[:, :, 2],
            jnp.full((batch, length), BOND_LEN_C_O, backbones.dtype),
            jnp.full((batch, length), BOND_ANG_CA_C_O, backbones.dtype),
            psis - np.pi,
        )
        cloud = cloud.at[:, :, 3].set(oxy)

    # NOTE: the reference pre-fills with `padding` (utils.py:233) but then
    # overwrites every slot (backbone + CA-parking), so no pad value survives;
    # the `padding` arg is kept for signature parity only.
    del padding
    return cloud
