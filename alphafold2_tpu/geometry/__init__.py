"""Geometry / structure post-processing layer.

Pure-jnp, jit-friendly re-design of the reference geometry stack
(`alphafold2_pytorch/utils.py`): distogram centering, stress-majorization MDS,
dihedrals + chirality fix, Kabsch alignment, structure metrics, atom masks,
NeRF side-chain building, and host-side PDB I/O.

Unlike the reference there is no torch/numpy dual-backend dispatch layer
(`utils.py:33-76`): every function is a single jnp implementation that jits,
vmaps, and differentiates; numpy arrays are accepted and converted on entry.
"""

from alphafold2_tpu.geometry.distogram import (center_distogram,
                                               distogram_confidence)
from alphafold2_tpu.geometry.mds import mds, mdscaling, MDScaling
from alphafold2_tpu.geometry.dihedral import get_dihedral, calc_phis
from alphafold2_tpu.geometry.kabsch import kabsch, Kabsch
from alphafold2_tpu.geometry.metrics import rmsd, gdt, tmscore, RMSD, GDT, TMscore
from alphafold2_tpu.geometry.masks import scn_backbone_mask, scn_cloud_mask
from alphafold2_tpu.geometry.sidechain import nerf, sidechain_container

__all__ = [
    "center_distogram",
    "distogram_confidence",
    "mds",
    "mdscaling",
    "MDScaling",
    "get_dihedral",
    "calc_phis",
    "kabsch",
    "Kabsch",
    "rmsd",
    "gdt",
    "tmscore",
    "RMSD",
    "GDT",
    "TMscore",
    "scn_backbone_mask",
    "scn_cloud_mask",
    "nerf",
    "sidechain_container",
]
