"""Host-side PDB I/O (pure Python — no mdtraj dependency).

Parity: reference `alphafold2_pytorch/utils.py:83-149` (`download_pdb`,
`clean_pdb`, `custom2pdb`), which shells out to curl and uses mdtraj. This is
deliberately a thin host-side plugin boundary: nothing here touches the TPU
compute path.
"""

from __future__ import annotations

import os
import subprocess
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from alphafold2_tpu.constants import AA_ORDER

# standard 3-letter residue names for our vocabulary
AA_THREE = {
    "A": "ALA", "C": "CYS", "D": "ASP", "E": "GLU", "F": "PHE",
    "G": "GLY", "H": "HIS", "I": "ILE", "K": "LYS", "L": "LEU",
    "M": "MET", "N": "ASN", "P": "PRO", "Q": "GLN", "R": "ARG",
    "S": "SER", "T": "THR", "V": "VAL", "W": "TRP", "Y": "TYR",
}
THREE_TO_ONE = {v: k for k, v in AA_THREE.items()}

BACKBONE_ATOM_NAMES = ("N", "CA", "C", "O")


@dataclass
class PdbAtom:
    serial: int
    name: str
    res_name: str
    chain_id: str
    res_seq: int
    xyz: np.ndarray
    element: str = ""
    bfactor: float = 0.0  # carries per-residue confidence (pLDDT-style)


@dataclass
class PdbStructure:
    atoms: List[PdbAtom] = field(default_factory=list)

    def coords(self) -> np.ndarray:
        return np.stack([a.xyz for a in self.atoms]) if self.atoms else np.zeros((0, 3))

    def select_chain(self, chain_id: str) -> "PdbStructure":
        return PdbStructure([a for a in self.atoms if a.chain_id == chain_id])

    def select_atoms(self, names) -> "PdbStructure":
        names = set(names)
        return PdbStructure([a for a in self.atoms if a.name in names])

    def chains(self) -> List[str]:
        seen = []
        for a in self.atoms:
            if a.chain_id not in seen:
                seen.append(a.chain_id)
        return seen

    def sequence(self) -> str:
        seq, last = [], None
        for a in self.atoms:
            key = (a.chain_id, a.res_seq)
            if key != last:
                seq.append(THREE_TO_ONE.get(a.res_name, "X"))
                last = key
        return "".join(seq)


def _parse_bfactor(line: str) -> float:
    # tolerant: files in the wild carry blanks or overflow markers ('******'
    # for B > 999.99) in cols 61-66 — junk must not abort the whole parse
    # (and the C++ fast parser's field_f likewise returns 0 on junk)
    try:
        return float(line[60:66])
    except (ValueError, IndexError):
        return 0.0


def parse_pdb(path: str) -> PdbStructure:
    """Parse ATOM records from a PDB file (first model only)."""
    atoms: List[PdbAtom] = []
    with open(path) as fh:
        for line in fh:
            if line.startswith("ENDMDL"):
                break
            if not line.startswith("ATOM"):
                continue
            atoms.append(
                PdbAtom(
                    serial=int(line[6:11]),
                    name=line[12:16].strip(),
                    res_name=line[17:20].strip(),
                    chain_id=line[21].strip() or "A",
                    res_seq=int(line[22:26]),
                    xyz=np.array(
                        [float(line[30:38]), float(line[38:46]), float(line[46:54])]
                    ),
                    element=line[76:78].strip(),
                    bfactor=_parse_bfactor(line),
                )
            )
    return PdbStructure(atoms)


def write_pdb(path: str, structure: PdbStructure) -> str:
    """Write ATOM records to a PDB file."""
    with open(path, "w") as fh:
        for a in structure.atoms:
            name = a.name if len(a.name) == 4 else f" {a.name:<3s}"
            fh.write(
                f"ATOM  {a.serial:5d} {name}{'':1s}{a.res_name:>3s} "
                f"{a.chain_id:1s}{a.res_seq:4d}    "
                f"{a.xyz[0]:8.3f}{a.xyz[1]:8.3f}{a.xyz[2]:8.3f}"
                f"{1.00:6.2f}{a.bfactor:6.2f}          {a.element:>2s}\n"
            )
        fh.write("END\n")
    return path


def coords_to_structure(
    coords,
    sequence: Optional[str] = None,
    atom_names=BACKBONE_ATOM_NAMES[:3],
    chain_id: str = "A",
    bfactors=None,
) -> PdbStructure:
    """Build a PdbStructure from (L, A, 3) or (L*A, 3) coordinates.

    Each residue gets `len(atom_names)` atoms; `sequence` is a one-letter
    string (defaults to poly-alanine). `bfactors`: optional per-residue
    values written to every atom of that residue (confidence convention:
    `distogram_confidence` x 100, pLDDT-style).
    """
    coords = np.asarray(coords, dtype=np.float64).reshape(-1, 3)
    n_per_res = len(atom_names)
    length = coords.shape[0] // n_per_res
    if sequence is None:
        sequence = "A" * length
    if bfactors is not None:
        bfactors = np.asarray(bfactors, dtype=np.float64).reshape(-1)
        if bfactors.shape[0] != length:
            raise ValueError(
                f"bfactors has {bfactors.shape[0]} entries for {length} "
                f"residues"
            )
    atoms = []
    serial = 1
    for i in range(length):
        res3 = AA_THREE.get(sequence[i].upper(), "ALA")
        for j, an in enumerate(atom_names):
            atoms.append(
                PdbAtom(
                    serial=serial,
                    name=an,
                    res_name=res3,
                    chain_id=chain_id,
                    res_seq=i + 1,
                    xyz=coords[i * n_per_res + j],
                    element=an[0],
                    bfactor=float(bfactors[i]) if bfactors is not None else 0.0,
                )
            )
            serial += 1
    return PdbStructure(atoms)


def coords_to_pdb(path: str, coords, sequence: Optional[str] = None, **kwargs) -> str:
    """Convenience: coordinates -> .pdb file (reference `custom2pdb` analog,
    without the RCSB scaffold download)."""
    return write_pdb(path, coords_to_structure(coords, sequence, **kwargs))


def download_pdb(name: str, route: str) -> str:
    """Download a PDB entry from RCSB (reference `utils.py:83-91`).

    Network access may be unavailable; raises RuntimeError on failure instead
    of silently writing an empty file.
    """
    url = f"https://files.rcsb.org/download/{name}.pdb"
    result = subprocess.run(
        ["curl", "-sf", "-o", route, url], capture_output=True, timeout=120
    )
    if result.returncode != 0 or not os.path.exists(route):
        raise RuntimeError(f"failed to download {url}: {result.stderr.decode()!r}")
    return route


def clean_pdb(name: str, route: Optional[str] = None, chain_id: Optional[str] = None) -> str:
    """Keep only ATOM records (optionally a single chain) — reference
    `utils.py:93-120` without the mdtraj dependency."""
    destin = route if route is not None else name
    structure = parse_pdb(name)
    if chain_id is not None:
        structure = structure.select_chain(chain_id)
    return write_pdb(destin, structure)
