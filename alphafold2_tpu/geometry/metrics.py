"""Structure-quality metrics: RMSD, GDT (TS/HA), TM-score.

Parity: reference `alphafold2_pytorch/utils.py:563-624,713-761`. The
reference iterates over GDT cutoffs in Python (`utils.py:585-586`); here the
cutoff axis is vectorized.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

GDT_TS_CUTOFFS = (1.0, 2.0, 4.0, 8.0)
GDT_HA_CUTOFFS = (0.5, 1.0, 2.0, 4.0)


def _batchify(*arrays):
    """Promote (3, N) inputs to (1, 3, N); outputs are always (batch,) —
    matching the reference wrapper semantics (`utils.py:42-60`)."""
    arrays = tuple(jnp.asarray(a) for a in arrays)
    if arrays[0].ndim == 2:
        return tuple(a[None] for a in arrays)
    return arrays


def _point_weights(mask, X):
    """(batch, N) float point weights and per-structure counts from an
    optional boolean mask; None means all points valid."""
    if mask is None:
        w = jnp.ones(X.shape[:-2] + X.shape[-1:], X.dtype)
    else:
        w = jnp.asarray(mask, X.dtype)
        if w.ndim == 1:
            w = w[None]
    return w, jnp.maximum(jnp.sum(w, axis=-1), 1.0)


def _check_norm_len(norm_len, mask, X):
    """Precondition: norm_len covers every scored point (it is the FULL
    reference length). A smaller value silently inflates GDT/TM-score
    above 1.0. Enforced eagerly when inputs are concrete; under a jit
    trace the mask sum is unavailable, so the callers ALSO clamp the
    normalizer at compute time (`_norm_len_clamped`) — jitted scores stay
    bounded even when this guard no-ops on tracers (ADVICE r5)."""
    if mask is None:
        valid = X.shape[-1]
    else:
        try:
            valid = int(np.max(np.sum(np.asarray(mask, np.float64), axis=-1)))
        except Exception:  # traced mask: cannot inspect values
            return
    if norm_len < valid:
        raise ValueError(
            f"norm_len={norm_len} is smaller than the scored point count "
            f"{valid}; the score would exceed 1.0. norm_len is the full "
            f"reference length and must cover every valid point.")


def _norm_len_clamped(norm_len, valid_count, X):
    """Trace-safe normalizer: norm_len, never below the per-structure
    scored point count. Eager misuse raises in `_check_norm_len`; under
    jit this clamp keeps GDT/TM <= 1.0 instead of silently exceeding it
    (the scores then normalize by the actual count — the defensible
    reading of an undersized norm_len)."""
    return jnp.maximum(jnp.asarray(float(norm_len), X.dtype), valid_count)


def rmsd(X, Y, mask=None):
    """Root-mean-square deviation. X, Y: (batch, 3, N) -> (batch,).
    `mask` (batch, N): points excluded from the average when False."""
    X, Y = _batchify(X, Y)
    w, n = _point_weights(mask, X)
    sq = jnp.sum((X - Y) ** 2, axis=-2)  # (batch, N)
    return jnp.sqrt(jnp.sum(sq * w, axis=-1) / (3.0 * n))


def gdt(X, Y, cutoffs=GDT_TS_CUTOFFS, weights=None, mask=None,
        norm_len=None):
    """Global distance test. X, Y: (batch, 3, N) -> (batch,).
    `weights`: per-cutoff weights; `mask` (batch, N): per-point validity.
    `norm_len`: normalize fractions by this reference length instead of the
    provided point count — CASP convention when scoring a prediction that
    covers only part of the reference (uncovered residues count as outside
    every cutoff)."""
    X, Y = _batchify(X, Y)
    cutoffs = jnp.asarray(cutoffs, dtype=X.dtype)
    if weights is None:
        weights = jnp.ones_like(cutoffs)
    else:
        weights = jnp.broadcast_to(jnp.asarray(weights, dtype=X.dtype), cutoffs.shape)
    pw, n = _point_weights(mask, X)
    if norm_len is not None:
        _check_norm_len(norm_len, mask, X)
        n = _norm_len_clamped(norm_len, n, X)
    dist = jnp.sqrt(jnp.sum((X - Y) ** 2, axis=-2))  # (batch, N)
    # fraction of valid residues within each cutoff, weighted mean over cutoffs
    within = (dist[..., None, :] <= cutoffs[:, None]).astype(X.dtype)
    frac = jnp.sum(within * pw[..., None, :], axis=-1) / n[..., None]  # (batch, K)
    return jnp.mean(frac * weights, axis=-1)


def tmscore(X, Y, mask=None, norm_len=None):
    """Template-modeling score. X, Y: (batch, 3, N) -> (batch,).

    Deviation from the reference (`utils.py:608-615`): d0 is clamped to
    >= 0.5 as in standard TM-score implementations — the unclamped formula
    goes negative near L=18 and collapses the score for short chains.
    With `mask`, L is the per-structure count of valid points. `norm_len`:
    use this reference length for BOTH d0 and the 1/L normalization
    (standard TM-score convention when the prediction covers only part of
    the reference — uncovered residues contribute zero terms).
    """
    X, Y = _batchify(X, Y)
    w, n = _point_weights(mask, X)
    if norm_len is not None:
        _check_norm_len(norm_len, mask, X)
        n = _norm_len_clamped(norm_len, n, X)
        d0 = jnp.asarray(
            max(1.24 * np.cbrt(norm_len - 15) - 1.8, 0.5)
            if norm_len > 15 else 0.5,
            X.dtype,
        )
    elif mask is None:
        L = X.shape[-1]
        d0 = max(1.24 * np.cbrt(L - 15) - 1.8, 0.5) if L > 15 else 0.5
        d0 = jnp.asarray(d0, X.dtype)
    else:
        d0 = jnp.maximum(1.24 * jnp.cbrt(jnp.maximum(n - 15.0, 1e-3)) - 1.8, 0.5)
    dist = jnp.sqrt(jnp.sum((X - Y) ** 2, axis=-2))
    terms = 1.0 / (1.0 + (dist / d0[..., None]) ** 2)
    return jnp.sum(terms * w, axis=-1) / n


# public wrappers (reference utils.py:713-761)

def RMSD(A, B, *, mask=None):
    return rmsd(A, B, mask=mask)


def GDT(A, B, *, mode: str = "TS", weights=None, mask=None, norm_len=None):
    cutoffs = GDT_HA_CUTOFFS if str(mode).upper() == "HA" else GDT_TS_CUTOFFS
    return gdt(A, B, cutoffs=cutoffs, weights=weights, mask=mask,
               norm_len=norm_len)


def TMscore(A, B, *, mask=None, norm_len=None):
    return tmscore(A, B, mask=mask, norm_len=norm_len)
