"""Structure-quality metrics: RMSD, GDT (TS/HA), TM-score.

Parity: reference `alphafold2_pytorch/utils.py:563-624,713-761`. The
reference iterates over GDT cutoffs in Python (`utils.py:585-586`); here the
cutoff axis is vectorized.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

GDT_TS_CUTOFFS = (1.0, 2.0, 4.0, 8.0)
GDT_HA_CUTOFFS = (0.5, 1.0, 2.0, 4.0)


def _batchify(*arrays):
    """Promote (3, N) inputs to (1, 3, N); outputs are always (batch,) —
    matching the reference wrapper semantics (`utils.py:42-60`)."""
    arrays = tuple(jnp.asarray(a) for a in arrays)
    if arrays[0].ndim == 2:
        return tuple(a[None] for a in arrays)
    return arrays


def rmsd(X, Y):
    """Root-mean-square deviation. X, Y: (batch, 3, N) -> (batch,)."""
    X, Y = _batchify(X, Y)
    return jnp.sqrt(jnp.mean((X - Y) ** 2, axis=(-1, -2)))


def gdt(X, Y, cutoffs=GDT_TS_CUTOFFS, weights=None):
    """Global distance test. X, Y: (batch, 3, N) -> (batch,)."""
    X, Y = _batchify(X, Y)
    cutoffs = jnp.asarray(cutoffs, dtype=X.dtype)
    if weights is None:
        weights = jnp.ones_like(cutoffs)
    else:
        weights = jnp.broadcast_to(jnp.asarray(weights, dtype=X.dtype), cutoffs.shape)
    dist = jnp.sqrt(jnp.sum((X - Y) ** 2, axis=-2))  # (batch, N)
    # fraction of residues within each cutoff, weighted mean over cutoffs
    frac = jnp.mean(
        (dist[..., None, :] <= cutoffs[:, None]).astype(X.dtype), axis=-1
    )  # (batch, K)
    return jnp.mean(frac * weights, axis=-1)


def tmscore(X, Y):
    """Template-modeling score. X, Y: (batch, 3, N) -> (batch,).

    Deviation from the reference (`utils.py:608-615`): d0 is clamped to
    >= 0.5 as in standard TM-score implementations — the unclamped formula
    goes negative near L=18 and collapses the score for short chains.
    """
    X, Y = _batchify(X, Y)
    L = X.shape[-1]
    d0 = max(1.24 * np.cbrt(L - 15) - 1.8, 0.5) if L > 15 else 0.5
    dist = jnp.sqrt(jnp.sum((X - Y) ** 2, axis=-2))
    return jnp.mean(1.0 / (1.0 + (dist / d0) ** 2), axis=-1)


# public wrappers (reference utils.py:713-761)

def RMSD(A, B):
    return rmsd(A, B)


def GDT(A, B, *, mode: str = "TS", weights=None):
    cutoffs = GDT_HA_CUTOFFS if str(mode).upper() == "HA" else GDT_TS_CUTOFFS
    return gdt(A, B, cutoffs=cutoffs, weights=weights)


def TMscore(A, B):
    return tmscore(A, B)
