"""Multidimensional scaling: distance matrix -> 3D coordinates.

Parity: reference `alphafold2_pytorch/utils.py:306-399,627-664` (`mds_torch`,
`mdscaling_torch`). Guttman-transform stress majorization.

TPU-first redesign: the reference runs a Python loop with a data-dependent
`break` (`utils.py:328-347`). Here the iteration is a `lax.scan` with a fixed
trip count and a convergence flag that freezes further updates — fully
jittable AND reverse-differentiable (the end-to-end loss backprops through
these iterations, reference `train_end2end.py:152-176`). Each Guttman step is
one batched (N, N) @ (N, 3) matmul — MXU-friendly.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from alphafold2_tpu.geometry.dihedral import calc_phis


def _pairwise_dist(coords, eps=1e-12):
    """Batched euclidean cdist with a grad-safe sqrt. coords: (b, N, 3)."""
    d2 = jnp.sum((coords[:, :, None, :] - coords[:, None, :, :]) ** 2, axis=-1)
    return jnp.sqrt(d2 + eps)


def _classical_init(pre_dist_mat):
    """Torgerson classical-MDS embedding as a Guttman warm start.

    Double-center the squared distances (B = -1/2 J D^2 J) and embed with
    the top-3 eigenpairs. For near-Euclidean inputs this lands within
    quantization noise of the global optimum, so the iterative tail
    refines instead of traveling from a random cloud — the same stress is
    reached in far fewer Guttman iterations (the reference's 200,
    utils.py:306, is sized for random init). One (N, N) eigh per
    structure — O(N^3) once, ~1.5 GFLOPs at N=1152, amortized against
    hundreds of sequential (N, N) matmul iterations it replaces.

    Detached: eigh's backward is unstable under (near-)degenerate
    eigenvalues, and the init is a starting POINT, not part of the
    differentiable pipeline (random init carries no gradient either);
    gradients flow through the Guttman iterations only.
    """
    d2 = jnp.square(pre_dist_mat)
    row = jnp.mean(d2, axis=-1, keepdims=True)
    col = jnp.mean(d2, axis=-2, keepdims=True)
    tot = jnp.mean(d2, axis=(-1, -2), keepdims=True)
    b_mat = -0.5 * (d2 - row - col + tot)
    evals, evecs = jnp.linalg.eigh(b_mat)  # ascending
    top_vals = jnp.clip(evals[..., -3:], 0.0)  # (b, 3)
    top_vecs = evecs[..., -3:]  # (b, N, 3)
    coords = top_vecs * jnp.sqrt(top_vals)[..., None, :]
    return jax.lax.stop_gradient(coords)


@partial(jax.jit, static_argnames=("iters", "bwd_iters", "unroll", "init"))
def mds(
    pre_dist_mat,
    weights=None,
    iters: int = 10,
    tol: float = 1e-5,
    key=None,
    bwd_iters: int | None = None,
    unroll: int = 1,
    init: str = "random",
):
    """Stress-majorization MDS.

    Args:
      pre_dist_mat: (batch, N, N) (or (N, N)) target distance matrix.
      weights: (batch, N, N) per-pair confidence; defaults to ones.
      iters: fixed iteration count (static for jit).
      tol: relative-improvement tolerance; once the mean improvement over the
        batch drops below it, updates freeze (mirrors the reference's break,
        `utils.py:343-347`).
      key: PRNG key for the random init (explicit, unlike the reference's
        implicit global RNG at `utils.py:326`).
      bwd_iters: if set (< iters), backprop is TRUNCATED to the last
        `bwd_iters` iterations: the earlier ones run under stop_gradient,
        and the differentiable tail ignores the convergence freeze (a frozen
        update would pass the detached carry through unchanged and zero the
        gradient). The gradient is the K-term truncation of the full
        unrolled chain — near the fixed point this approximates implicit
        differentiation (each extra term is a power of the contractive
        Guttman-map Jacobian) — while the backward stores and traverses K
        instead of `iters` per-iteration (N, N) residuals. Forward deviates
        from the default path only when the freeze would have fired: the
        tail's extra Guttman steps move coords by at most K x the
        (tol-scale) per-iteration movement at freeze. bwd_iters=0 detaches MDS entirely
        (no gradient to distances/weights). The end-to-end loss backprops
        through MDS (reference train_end2end.py:152-176), where iters=200
        makes the full unroll the dominant memory/latency cost.
      unroll: lax.scan unroll factor. The 200 iterations are sequential
        (1152, 1152)-scale ops at batch 1 — dispatch-overhead territory on
        TPU (PERF.md "MDS latency"); unrolling amortizes per-iteration loop
        overhead at the cost of compile time. Same math and trip count;
        results differ from the rolled scan only by XLA
        fusion/reassociation float noise.
      init: "random" (reference parity, utils.py:326) or "classical" —
        Torgerson double-centering eigendecomposition warm start
        (_classical_init), which reaches the random-init stress floor in
        a fraction of the iterations and is the lever for cutting
        `iters` below the reference's 200.

    Returns:
      coords: (batch, 3, N)
      stress_history: (iters, batch) normalized stress per iteration (frozen
        value repeated after convergence).
    """
    pre_dist_mat = jnp.asarray(pre_dist_mat)
    if pre_dist_mat.ndim == 2:
        pre_dist_mat = pre_dist_mat[None]
    batch, n, _ = pre_dist_mat.shape

    if weights is None:
        weights = jnp.ones_like(pre_dist_mat)
    if key is None:
        key = jax.random.PRNGKey(0)

    if init == "classical":
        init_coords = _classical_init(pre_dist_mat)
    elif init == "random":
        init_coords = (
            2.0 * jax.random.uniform(key, (batch, n, 3), pre_dist_mat.dtype)
            - 1.0
        )
    else:
        raise ValueError(f"unknown mds init {init!r}")
    eye = jnp.eye(n, dtype=pre_dist_mat.dtype)

    def make_step(allow_freeze: bool):
        def step(carry, _):
            coords, best_stress, done = carry
            dist = _pairwise_dist(coords)
            stress = 0.5 * jnp.sum(weights * (dist - pre_dist_mat) ** 2, axis=(-1, -2))
            # Guttman transform (reference utils.py:333-338)
            dist = jnp.where(dist == 0.0, 1e-7, dist)
            ratio = weights * (pre_dist_mat / dist)
            B = -ratio + eye[None] * jnp.sum(ratio, axis=-1, keepdims=True)
            new_coords = jnp.matmul(B, coords) / n
            dis = jnp.linalg.norm(new_coords, axis=(-1, -2))
            norm_stress = stress / dis
            improvement = jnp.mean(best_stress - norm_stress)
            if allow_freeze:
                # once converged, the update is not taken (mirrors the
                # reference's break-before-assign at utils.py:343-350)
                new_done = done | (improvement <= tol)
                coords = jnp.where(new_done, coords, new_coords)
                best_stress = jnp.where(new_done, best_stress, norm_stress)
            else:
                # differentiable tail of the truncated-backprop path: keep
                # updating even past convergence. A frozen update would be a
                # pure pass-through of the stop_gradient'd carry — the
                # gradient through coords would be identically ZERO whenever
                # convergence fires before the cut, which at iters=200 /
                # tol=1e-5 is the common case. Extra Guttman steps at a
                # converged point are near-no-ops forward, so this costs only
                # a small (K x tol-scale-step) forward deviation from the
                # freeze semantics.
                new_done = done
                best_stress = norm_stress
                coords = new_coords
            return (coords, best_stress, new_done), best_stress

        return step

    best_stress0 = jnp.full((batch,), jnp.inf, pre_dist_mat.dtype)
    carry = (init_coords, best_stress0, jnp.array(False))

    if bwd_iters is not None and bwd_iters < iters:
        carry, head = jax.lax.scan(
            make_step(True), carry, None, length=iters - bwd_iters,
            unroll=unroll,
        )
        # cut the chain: no gradient flows into (or residuals are kept for)
        # the first iters-bwd_iters steps. `done` is boolean (no gradient).
        # The history rows of the head are detached too, so a loss touching
        # them cannot silently re-materialize all head residuals.
        head = jax.lax.stop_gradient(head)
        carry = jax.tree_util.tree_map(jax.lax.stop_gradient, carry)
        if bwd_iters == 0:
            # explicit opt-out of MDS gradients entirely
            history = head
        else:
            carry, tail = jax.lax.scan(
                make_step(False), carry, None, length=bwd_iters,
                unroll=unroll,
            )
            history = jnp.concatenate([head, tail], axis=0)
    else:
        carry, history = jax.lax.scan(
            make_step(True), carry, None, length=iters, unroll=unroll
        )

    coords = carry[0]
    return jnp.transpose(coords, (0, 2, 1)), history


def mdscaling(
    pre_dist_mat,
    weights=None,
    iters: int = 10,
    tol: float = 1e-5,
    fix_mirror: bool = True,
    N_mask=None,
    CA_mask=None,
    C_mask=None,
    key=None,
    bwd_iters: int | None = None,
    unroll: int = 1,
    init: str = "random",
):
    """MDS + chirality (mirror-image) correction.

    Parity: reference `utils.py:627-644`. MDS is reflection-ambiguous; real
    protein backbones have mostly-negative phi dihedrals, so if fewer than
    half the phis are negative the Z axis is flipped. The reference applies
    one batch-global flip decision (`utils.py:637-642`, effectively batch=1);
    here the flip is decided per structure with `jnp.where` — jit-friendly and
    correct for batch > 1.
    """
    preds, stresses = mds(
        pre_dist_mat, weights=weights, iters=iters, tol=tol, key=key,
        bwd_iters=bwd_iters, unroll=unroll, init=init,
    )
    if not fix_mirror:
        return preds, stresses
    if N_mask is None or CA_mask is None:
        raise ValueError(
            "fix_mirror=True requires N_mask and CA_mask (backbone atom masks); "
            "pass fix_mirror=False to skip chirality correction"
        )

    phi_ratios = calc_phis(preds, N_mask, CA_mask, C_mask, prop=True)
    flip = (phi_ratios < 0.5)[:, None]  # (batch, 1)
    z_flipped = jnp.where(flip, -preds[:, -1], preds[:, -1])
    preds = preds.at[:, -1].set(z_flipped)
    return preds, stresses


def MDScaling(pre_dist_mat, **kwargs):
    """Public wrapper, reference `utils.py:671-696` (backend-agnostic there;
    single jnp implementation here). Accepts (N, N) or (batch, N, N)."""
    return mdscaling(pre_dist_mat, **kwargs)
