"""Atom-presence masks for dense (residue, atom-slot) coordinate clouds.

Parity: reference `alphafold2_pytorch/utils.py:154-189` (`scn_cloud_mask`,
`scn_backbone_mask`). The reference fills the cloud mask with a Python loop
over residues (`utils.py:164-168`); here it is a vectorized table lookup that
jits.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from alphafold2_tpu.constants import ATOMS_PER_TOKEN, NUM_COORDS_PER_RES


def scn_cloud_mask(seq_tokens, boolean: bool = True, n_atoms: int = NUM_COORDS_PER_RES):
    """Per-residue atom-slot presence mask.

    Args:
      seq_tokens: (batch, L) integer amino-acid tokens (our vocabulary,
        `constants.AA_ORDER`).
      boolean: return bool mask (True) or indices (False).

    Returns: (batch, L, n_atoms) bool — slot s is present iff
      s < heavy_atom_count(residue).
    """
    seq_tokens = jnp.asarray(seq_tokens)
    counts = jnp.asarray(ATOMS_PER_TOKEN)[seq_tokens]  # (batch, L)
    mask = jnp.arange(n_atoms)[None, None, :] < counts[..., None]
    if boolean:
        return mask
    return jnp.argwhere(mask)


def scn_backbone_mask(seq_tokens, boolean: bool = True, l_aa: int = NUM_COORDS_PER_RES):
    """(N_mask, CA_mask) over a flattened (L * l_aa) atom axis.

    N is atom 0 of each residue, C-alpha is atom 1 (reference
    `utils.py:180-189`). Returned as numpy so they can serve as *static*
    masks for `calc_phis` under jit. Only the token SHAPE is read, so
    traced arrays are fine (the masks stay host-side constants).
    """
    length = seq_tokens.shape[-1] * l_aa
    pos = np.arange(length)
    N_mask = pos % l_aa == 0
    CA_mask = pos % l_aa == 1
    if boolean:
        return N_mask, CA_mask
    return np.nonzero(N_mask)[0], np.nonzero(CA_mask)[0]
