"""Dihedral angles and backbone chirality (phi) statistics.

Parity: reference `alphafold2_pytorch/utils.py:401-508`
(`get_dihedral_torch`, `calc_phis_torch`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def get_dihedral(c1, c2, c3, c4):
    """Dihedral angle (radians) between planes (c1,c2,c3) and (c2,c3,c4).

    atan2 formulation (polymer-physics convention), matching reference
    `utils.py:401-417`. Inputs are (..., 3); broadcasting is supported.
    """
    c1, c2, c3, c4 = map(jnp.asarray, (c1, c2, c3, c4))
    u1 = c2 - c1
    u2 = c3 - c2
    u3 = c4 - c3

    y = jnp.sum(
        (jnp.linalg.norm(u2, axis=-1, keepdims=True) * u1) * jnp.cross(u2, u3), axis=-1
    )
    x = jnp.sum(jnp.cross(u1, u2) * jnp.cross(u2, u3), axis=-1)
    return jnp.arctan2(y, x)


def calc_phis(pred_coords, N_mask, CA_mask, C_mask=None, prop: bool = True):
    """Backbone phi angles (or the fraction that are negative).

    Used for chirality detection: a correctly-handed backbone has mostly
    negative phi. Parity: reference `utils.py:437-471` — including the
    gradient stop (reference detaches before the angle computation,
    `utils.py:454`); here `stop_gradient` keeps everything on-device instead
    of forcing a GPU->CPU sync.

    Args:
      pred_coords: (batch, 3, P) coordinates over P backbone points.
      N_mask, CA_mask, C_mask: (P,) boolean masks selecting N / C-alpha /
        C-term atoms. Must be *static* (numpy) so shapes stay static under
        jit. If C_mask is None it is ~(N | CA).
      prop: return the per-structure fraction of negative phis.

    Returns: (batch,) proportions if prop else (batch, L-1) phi angles.
    """
    coords = jnp.transpose(jax.lax.stop_gradient(jnp.asarray(pred_coords)), (0, 2, 1))

    N_mask = np.asarray(N_mask).reshape(-1).astype(bool)
    CA_mask = np.asarray(CA_mask).reshape(-1).astype(bool)
    if C_mask is None:
        C_mask = ~(N_mask | CA_mask)
    else:
        C_mask = np.asarray(C_mask).reshape(-1).astype(bool)

    n_terms = coords[:, N_mask]
    c_alphas = coords[:, CA_mask]
    c_terms = coords[:, C_mask]

    # phi_i between planes (C_{i-1}, N_i, CA_i) and (N_i, CA_i, C_i)
    phis = get_dihedral(
        c_terms[:, :-1], n_terms[:, 1:], c_alphas[:, 1:], c_terms[:, 1:]
    )

    if prop:
        return jnp.mean((phis < 0.0).astype(jnp.float32), axis=-1)
    return phis
