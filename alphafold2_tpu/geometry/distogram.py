"""Distogram -> distance-matrix centering.

Parity: reference `alphafold2_pytorch/utils.py:260-302`
(`center_distogram_torch`). Converts a per-pair distance *distribution* over
buckets into a central distance estimate plus confidence weights used by MDS.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from alphafold2_tpu.constants import DISTANCE_THRESHOLDS


def _bin_centers(bins: jnp.ndarray) -> jnp.ndarray:
    """Centers of distance buckets given their upper thresholds.

    Matches reference `utils.py:273-275`: shift thresholds down by half a bin
    width, clamp the first center to 1.5 A, and push the last (catch-all
    "far") bucket to 1.33x the final threshold.
    """
    centers = bins - 0.5 * (bins[2] - bins[1])
    centers = centers.at[0].set(1.5)
    centers = centers.at[-1].set(1.33 * bins[-1])
    return centers


def center_distogram(
    distogram,
    bins=None,
    center: str = "mean",
    wide: str = "std",
):
    """Central distance estimate + confidence weights from a distogram.

    Args:
      distogram: (batch, N, N, B) probabilities over B distance buckets
        (softmax the logits first).
      bins: (B,) bucket thresholds; defaults to linspace(2, 20, 37).
      center: "mean" (expectation over bin centers) or "median"
        (bucket whose CDF crosses 0.5).
      wide: dispersion measure for the weights — "std", "var", or "none".

    Returns:
      central: (batch, N, N) distances, zero diagonal.
      weights: (batch, N, N) confidence in [0, 1]; 0 where the central
        estimate falls in the catch-all "far" bucket.
    """
    distogram = jnp.asarray(distogram)
    if distogram.ndim == 3:
        distogram = distogram[None]
    bins = jnp.asarray(DISTANCE_THRESHOLDS if bins is None else bins, dtype=distogram.dtype)

    centers = _bin_centers(bins)
    n = distogram.shape[-2]

    if center == "median":
        cum = jnp.cumsum(distogram, axis=-1)
        # index of the first bucket whose CDF reaches 0.5 (reference
        # utils.py:279-282 via searchsorted)
        idx = jnp.sum((cum < 0.5).astype(jnp.int32), axis=-1)
        idx = jnp.minimum(idx, centers.shape[0] - 1)
        central = centers[idx]
    elif center == "mean":
        central = jnp.einsum("...b,b->...", distogram, centers)
    else:
        raise ValueError(f"unknown center mode {center!r}")

    # pairs predicted beyond the last real threshold carry no signal
    # (reference utils.py:286)
    mask = (central <= bins[-2]).astype(distogram.dtype)

    # the self-distance is exactly zero (reference utils.py:288-290)
    eye = jnp.eye(n, dtype=bool)
    central = jnp.where(eye[None], 0.0, central)

    if wide == "var":
        dispersion = jnp.einsum(
            "...b,...b->...", distogram, (centers - central[..., None]) ** 2
        )
    elif wide == "std":
        dispersion = jnp.sqrt(
            jnp.einsum(
                "...b,...b->...", distogram, (centers - central[..., None]) ** 2
            )
        )
    else:
        dispersion = jnp.zeros_like(central)

    weights = mask / (1.0 + dispersion)
    weights = jnp.nan_to_num(weights, nan=0.0)
    return central, weights


def distogram_confidence(distogram, mask=None):
    """Per-residue confidence in [0, 1] from distogram entropy.

    The reference exposes no confidence signal at all; structure-prediction
    users expect one (AlphaFold's pLDDT convention). This is the natural
    distogram analog: residue i's confidence is the mean over partners j of
    the model's CERTAINTY about the (i, j) distance, where certainty is one
    minus the normalized entropy of the bucket distribution —
    1 - H(p_ij)/ln(B). A uniform distogram scores 0, a one-hot distogram 1.
    Written into PDB B-factors (scaled x100, pLDDT-style) by predict.py.

    Args:
      distogram: (batch, N, N, B) probabilities (softmax the logits first).
      mask: (batch, N) bool residue validity; masked partners are excluded
        from every mean and masked residues score 0.

    Returns: (batch, N) float32.
    """
    distogram = jnp.asarray(distogram)
    if distogram.ndim == 3:
        distogram = distogram[None]
    p = distogram.astype(jnp.float32)
    n, nb = p.shape[-2], p.shape[-1]
    ent = -jnp.sum(p * jnp.log(jnp.clip(p, 1e-12)), axis=-1)  # (b, N, N)
    # nb=1 is degenerate (ent=0, ln(1)=0 -> 0/0): a single-bucket distogram
    # carries no distance information, so certainty is defined as 1 (the
    # distribution is exactly known) rather than NaN
    if nb == 1:
        certainty = jnp.ones_like(ent)
    else:
        certainty = 1.0 - ent / jnp.log(float(nb))

    off_diag = ~jnp.eye(n, dtype=bool)[None]
    if mask is not None:
        mask = jnp.asarray(mask, dtype=bool)
        pair_valid = off_diag & mask[:, :, None] & mask[:, None, :]
    else:
        pair_valid = jnp.broadcast_to(off_diag, certainty.shape)
    denom = jnp.maximum(jnp.sum(pair_valid, axis=-1), 1)
    conf = jnp.sum(jnp.where(pair_valid, certainty, 0.0), axis=-1) / denom
    if mask is not None:
        conf = jnp.where(mask, conf, 0.0)
    return jnp.clip(conf, 0.0, 1.0)


def bucketize_distances(coords, mask=None, bins=None, ignore_index: int = -100):
    """Ground-truth bucketized distance labels for distogram training.

    Parity: reference `train_pre.py:35-40` (`get_bucketed_distance_matrix`).

    Args:
      coords: (batch, N, 3) C-alpha coordinates.
      mask: (batch, N) bool validity mask.
      bins: (B,) bucket thresholds.
      ignore_index: label for masked-out pairs.

    Returns: (batch, N, N) int32 bucket labels in [0, B-1] or ignore_index.
    """
    coords = jnp.asarray(coords)
    bins = jnp.asarray(DISTANCE_THRESHOLDS if bins is None else bins, dtype=coords.dtype)
    d2 = jnp.sum((coords[:, :, None, :] - coords[:, None, :, :]) ** 2, axis=-1)
    dist = jnp.sqrt(jnp.maximum(d2, 1e-12))
    labels = jnp.searchsorted(bins[:-1], dist).astype(jnp.int32)
    if mask is not None:
        mask = jnp.asarray(mask, dtype=bool)
        pair_mask = mask[:, :, None] & mask[:, None, :]
        labels = jnp.where(pair_mask, labels, np.int32(ignore_index))
    return labels
