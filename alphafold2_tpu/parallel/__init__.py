"""Parallelism layer: device meshes, sharding rules, distributed training.

TPU-native replacement for the reference's (empty) DeepSpeed/Lightning
distribution story (reference training_scripts/, install_deepspeed.sh):
`jax.sharding.Mesh` + GSPMD annotations, with XLA emitting the ICI/DCN
collectives. See SURVEY.md §2.2 for the strategy-by-strategy mapping.
"""

from alphafold2_tpu.parallel.mesh import data_parallel_mesh, hybrid_mesh, make_mesh
from alphafold2_tpu.parallel.rules import (
    TP_RULES,
    match_partition_rules,
    named_tree_map,
    partition_rules,
    rule_axes,
    tree_path_string,
    unmatched_leaves,
)
from alphafold2_tpu.parallel.sharding import (
    batch_shardings,
    param_spec,
    replicated,
    state_shardings,
)
from alphafold2_tpu.parallel.overlap import (
    flatten_buckets,
    overlap_enabled,
    plan_buckets,
    unflatten_buckets,
)
from alphafold2_tpu.parallel.train import (
    make_dp_overlap_train_step,
    make_multihost_train_step,
    make_sharded_train_step,
    make_sp_train_step,
    make_pp_train_step,
    pp_distogram_loss_fn,
    pp_e2e_loss_fn,
    pp_model_apply,
    pp_train_state_init,
    sp_e2e_loss_fn,
    sp_model_apply,
    sp_distogram_loss_fn,
    sharded_train_state_init,
)
from alphafold2_tpu.parallel.sequence import (
    axial_alltoall_transpose,
    ring_attention,
    sequence_parallel_axial_attention,
    tied_row_attention_sharded,
    ulysses_attention,
)
from alphafold2_tpu.parallel.sp_trunk import (
    alphafold2_apply_sp,
    msa_sharded_trunk_apply,
    sp_trunk_apply,
)
from alphafold2_tpu.parallel.pipeline import (
    alphafold2_apply_pp,
    pipeline_trunk_apply,
)
from alphafold2_tpu.parallel.distributed import (
    distributed_startup,
    global_mesh,
    initialize_from_env,
    process_local_batch_size,
)

__all__ = [
    "sp_trunk_apply",
    "msa_sharded_trunk_apply",
    "alphafold2_apply_sp",
    "alphafold2_apply_pp",
    "pipeline_trunk_apply",
    "initialize_from_env",
    "distributed_startup",
    "global_mesh",
    "process_local_batch_size",
    "TP_RULES",
    "match_partition_rules",
    "named_tree_map",
    "partition_rules",
    "rule_axes",
    "tree_path_string",
    "unmatched_leaves",
    "make_multihost_train_step",
    "ring_attention",
    "ulysses_attention",
    "axial_alltoall_transpose",
    "sequence_parallel_axial_attention",
    "tied_row_attention_sharded",
    "make_mesh",
    "data_parallel_mesh",
    "hybrid_mesh",
    "param_spec",
    "state_shardings",
    "batch_shardings",
    "replicated",
    "flatten_buckets",
    "overlap_enabled",
    "plan_buckets",
    "unflatten_buckets",
    "make_dp_overlap_train_step",
    "make_sharded_train_step",
    "make_sp_train_step",
    "make_pp_train_step",
    "pp_distogram_loss_fn",
    "pp_e2e_loss_fn",
    "pp_model_apply",
    "pp_train_state_init",
    "sp_e2e_loss_fn",
    "sp_model_apply",
    "sp_distogram_loss_fn",
    "sharded_train_state_init",
]
