"""Multi-host runtime entry: `jax.distributed` + process-spanning meshes.

The reference's multi-node story is an empty DeepSpeed launcher
(reference training_scripts/deepspeed.py, 0 bytes) that would have carried
NCCL underneath. The TPU-native runtime is the JAX distributed service:
every host runs the same program, `jax.distributed.initialize` wires them
into one runtime, and `jax.devices()` then spans the whole pod — meshes,
shardings, and collectives (psum over DCN/ICI) work unchanged
(SURVEY.md §2.2, communication backend row).

Launch contract (one command per host):

    AF2_COORDINATOR=host0:8476 AF2_NUM_PROCESSES=4 AF2_PROCESS_ID=$i \\
        python train_pre.py ...

On Cloud TPU pods the three variables can be omitted entirely —
`jax.distributed.initialize()` auto-detects the topology — pass
`AF2_AUTO_INIT=1` to opt into that. Single-process runs need nothing: with
no coordinator configured `initialize_from_env` is a no-op.

Verified by a real 2-process CPU smoke test (tests/test_distributed.py):
two OS processes x 4 virtual devices form one 8-device mesh and reduce a
process-sharded array to the same global sum on both hosts.
"""

from __future__ import annotations

import os
from typing import Mapping, Optional

import jax

from alphafold2_tpu import compat


def initialize_from_env(
    *,
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids=None,
) -> bool:
    """Join the multi-host runtime if one is configured; else no-op.

    Reads AF2_COORDINATOR / AF2_NUM_PROCESSES / AF2_PROCESS_ID (explicit
    args win), or AF2_AUTO_INIT=1 for TPU-pod auto-detection — all
    parsed by ops/knobs.py, the one home for every AF2_* knob. Must run
    before any backend-initializing JAX call. Returns True when the
    distributed runtime was initialized.
    """
    from alphafold2_tpu.ops import knobs

    coordinator = coordinator or knobs.coordinator()
    if num_processes is None:
        num_processes = knobs.num_processes()
    if process_id is None:
        process_id = knobs.process_id()

    will_init = (coordinator and num_processes > 1) or knobs.auto_init()
    if will_init and compat.backend_initialized():
        # joining AFTER backend init would leave this process on its
        # local-only device view while claiming pod membership — every
        # mesh built from jax.devices() would silently be a one-host
        # mesh. Refuse loudly; the fix is ordering, not retrying.
        raise RuntimeError(
            "initialize_from_env() called after JAX's backend was already "
            "initialized — the distributed runtime must be joined BEFORE "
            "the first backend-initializing JAX call (jax.devices(), any "
            "computation, ...). Move the startup call (see "
            "distributed_startup) to the top of main()."
        )

    if coordinator and num_processes > 1:
        # CPU pods (the test matrix, accelerator-free hosts) need a
        # cross-process collectives impl picked before backend init;
        # harmless on non-CPU backends, so no platform sniffing — the
        # env var may be unset with the backend still resolving to CPU
        compat.enable_cpu_collectives()
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
        )
        return True
    if knobs.auto_init():
        jax.distributed.initialize()  # TPU-pod metadata auto-detection
        return True
    return False


def distributed_startup(label: str = "") -> bool:
    """The shared CLI startup: every entry point (train_pre.py,
    train_end2end.py, serve.py, predict.py) calls this once, right after
    argparse and before anything that initializes the JAX backend.

    Joins the multi-host runtime when one is configured (the
    AF2_COORDINATOR/... contract above), errors LOUDLY if the backend
    was already initialized (see initialize_from_env), and prints one
    line describing the joined topology so multi-host logs self-identify
    their process. Returns True when a distributed runtime was joined.
    """
    joined = initialize_from_env()
    if joined:
        tag = f"{label}: " if label else ""
        print(
            f"{tag}joined multi-host runtime: process "
            f"{jax.process_index()}/{jax.process_count()}, "
            f"{jax.local_device_count()} local / {jax.device_count()} "
            "global devices",
            flush=True,
        )
    return joined


# --- CPU-pod rehearsal harness ----------------------------------------------
# One definition of "launch N coordinated CPU processes" shared by the
# 2-process test matrix (tests/test_distributed.py) and the MULTICHIP
# dryrun's multihost_dp leg (__graft_entry__.py) — the env hygiene here
# (axon scrub, no inherited XLA flags, NO shared persistent compile
# cache: an executable cached under one process topology must never be
# replayed under another) was learned the hard way and must not drift
# between the two callers.


def free_local_port() -> int:
    """An OS-assigned free TCP port for a localhost coordinator."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def cpu_pod_env(
    *,
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    repo_path: Optional[str] = None,
    extra: Optional[Mapping[str, str]] = None,
) -> dict:
    """Scrubbed subprocess env for one process of a CPU-pod rehearsal.

    Pins the CPU platform, removes the TPU-tunnel pin, inherited XLA
    flags (workers provision their own virtual device counts), and any
    persistent compile-cache dir (topology aliasing hazard — see module
    comment). With `coordinator` set, adds the AF2_COORDINATOR /
    AF2_NUM_PROCESSES / AF2_PROCESS_ID launch contract; `extra` wins
    over everything.
    """
    env = dict(os.environ)
    for var in (
        "PALLAS_AXON_POOL_IPS",
        "JAX_PLATFORM_NAME",
        "JAX_COMPILATION_CACHE_DIR",
        "XLA_FLAGS",
    ):
        env.pop(var, None)
    env["JAX_PLATFORMS"] = "cpu"
    if coordinator is not None:
        env["AF2_COORDINATOR"] = coordinator
        env["AF2_NUM_PROCESSES"] = str(num_processes)
        env["AF2_PROCESS_ID"] = str(process_id)
    if repo_path:
        env["PYTHONPATH"] = os.pathsep.join(
            [repo_path] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else [])
        )
    env.update(dict(extra) if extra else {})
    return env


def global_mesh(axes: Mapping[str, int]):
    """Mesh over ALL processes' devices (call after initialize_from_env).

    Axis sizes must multiply to the global device count; the per-host batch
    a data loader should feed is global_batch * local_device_count /
    device_count.
    """
    from alphafold2_tpu.parallel.mesh import make_mesh

    return make_mesh(axes, jax.devices())


def process_local_batch_size(global_batch: int) -> int:
    """This host's share of a globally-sharded batch axis."""
    if global_batch % jax.process_count() != 0:
        raise ValueError(
            f"global batch {global_batch} must divide across "
            f"{jax.process_count()} processes"
        )
    return global_batch // jax.process_count()
