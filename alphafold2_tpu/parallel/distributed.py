"""Multi-host runtime entry: `jax.distributed` + process-spanning meshes.

The reference's multi-node story is an empty DeepSpeed launcher
(reference training_scripts/deepspeed.py, 0 bytes) that would have carried
NCCL underneath. The TPU-native runtime is the JAX distributed service:
every host runs the same program, `jax.distributed.initialize` wires them
into one runtime, and `jax.devices()` then spans the whole pod — meshes,
shardings, and collectives (psum over DCN/ICI) work unchanged
(SURVEY.md §2.2, communication backend row).

Launch contract (one command per host):

    AF2_COORDINATOR=host0:8476 AF2_NUM_PROCESSES=4 AF2_PROCESS_ID=$i \\
        python train_pre.py ...

On Cloud TPU pods the three variables can be omitted entirely —
`jax.distributed.initialize()` auto-detects the topology — pass
`AF2_AUTO_INIT=1` to opt into that. Single-process runs need nothing: with
no coordinator configured `initialize_from_env` is a no-op.

Verified by a real 2-process CPU smoke test (tests/test_distributed.py):
two OS processes x 4 virtual devices form one 8-device mesh and reduce a
process-sharded array to the same global sum on both hosts.
"""

from __future__ import annotations

import os
from typing import Mapping, Optional

import jax


def initialize_from_env(
    *,
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids=None,
) -> bool:
    """Join the multi-host runtime if one is configured; else no-op.

    Reads AF2_COORDINATOR / AF2_NUM_PROCESSES / AF2_PROCESS_ID (explicit
    args win), or AF2_AUTO_INIT=1 for TPU-pod auto-detection. Must run
    before any backend-initializing JAX call. Returns True when the
    distributed runtime was initialized.
    """
    coordinator = coordinator or os.environ.get("AF2_COORDINATOR")
    if num_processes is None:
        num_processes = int(os.environ.get("AF2_NUM_PROCESSES", "0") or 0)
    if process_id is None:
        pid_env = os.environ.get("AF2_PROCESS_ID")
        process_id = int(pid_env) if pid_env is not None else None

    if coordinator and num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
        )
        return True
    if os.environ.get("AF2_AUTO_INIT") == "1":
        jax.distributed.initialize()  # TPU-pod metadata auto-detection
        return True
    return False


def global_mesh(axes: Mapping[str, int]):
    """Mesh over ALL processes' devices (call after initialize_from_env).

    Axis sizes must multiply to the global device count; the per-host batch
    a data loader should feed is global_batch * local_device_count /
    device_count.
    """
    from alphafold2_tpu.parallel.mesh import make_mesh

    return make_mesh(axes, jax.devices())


def process_local_batch_size(global_batch: int) -> int:
    """This host's share of a globally-sharded batch axis."""
    if global_batch % jax.process_count() != 0:
        raise ValueError(
            f"global batch {global_batch} must divide across "
            f"{jax.process_count()} processes"
        )
    return global_batch // jax.process_count()
