"""Sequence-parallel trunk: the full dual-track trunk under `shard_map`.

Round-1 shipped the SP primitives (parallel/sequence.py) but no model path
used them (VERDICT r1 missing #3). This module runs the REAL trunk layer —
pair axial self-attention, (tied-row) MSA axial self-attention, both flat
cross-attentions, feed-forwards — with the pair grid's ROW axis and the MSA
ROW axis sharded over one mesh axis, inside a single `shard_map`:

  * pair self-attention  -> `sequence_parallel_axial_attention`
    (row pass local, column pass via all_to_all grid transpose);
  * MSA self-attention   -> tied rows: `tied_row_attention_sharded`
    (logit psum over the row shards) for the along-columns pass + an
    all_to_all transpose for the along-rows pass; untied: the same
    axial primitive as the pair grid;
  * pair<-MSA cross      -> the MSA stream is small: one all_gather of the
    context, then local dense cross-attention over the resident pair rows;
  * MSA<-pair cross      -> the pair stream is the big one: ring
    cross-attention — resident MSA queries stream the pair K/V shards
    around the ring (`ppermute`), nothing is ever gathered;
  * feed-forwards, norms, residuals — elementwise, shard-local.

Semantics match the replicated sequential trunk (cross_attn_mode="flat",
dropout off) to float tolerance; `tests/test_sp_trunk.py` asserts
full-model parity on the 8-device CPU mesh. KV compression for
cross-attention applies per-shard and therefore requires the local key
length to divide the ratio (checked).

Reference anchor: the axial fold-into-batch pattern this shards is
reference alphafold2_pytorch/alphafold2.py:240-286; SURVEY.md §2.2 maps it
to exactly this decomposition.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from alphafold2_tpu.models.config import Alphafold2Config
from alphafold2_tpu.ops.attention import attention_apply
from alphafold2_tpu.ops.core import layer_norm, linear
from alphafold2_tpu.parallel.sequence import (
    axial_alltoall_transpose,
    ring_attention,
    sequence_parallel_axial_attention,
    tied_row_attention_sharded,
)


def _split_heads(t, heads, dim_head):
    b, n, _ = t.shape
    return t.reshape(b, n, heads, dim_head)


def _msa_self_attention(params, cfg: Alphafold2Config, m, axis_name, msa_mask):
    """MSA axial self-attention with the ROW axis sharded.

    m: (b, r_local, c, d). Two passes, summed (ops/attention.py
    axial_attention_apply semantics):
      * along-columns pass — tied over ALL rows via the sharded-logit psum
        when cfg.msa_tie_row_attn, else plain attention with rows folded;
      * along-rows pass — all_to_all transpose to column shards, attend
        over the full row axis, transpose back.
    """
    attn_cfg = cfg.self_attn_config()
    b, r_local, c, d = m.shape

    # along-columns pass (the reference's tied "row attention",
    # alphafold2.py:280-282)
    if cfg.msa_tie_row_attn:
        row_out = tied_row_attention_sharded(
            params["attn_height"], attn_cfg, m, axis_name, mask=msa_mask
        )
    else:
        row_x = m.reshape(b * r_local, c, d)
        row_mask = msa_mask.reshape(b * r_local, c) if msa_mask is not None else None
        row_out = attention_apply(
            params["attn_height"], attn_cfg, row_x, mask=row_mask
        ).reshape(b, r_local, c, d)

    # along-rows pass: flip the sharded axis rows -> cols, fold cols
    mc = axial_alltoall_transpose(m, axis_name, row_sharded=True)  # (b, R, c_loc, d)
    r_full, c_local = mc.shape[1], mc.shape[2]
    if msa_mask is not None:
        mm = axial_alltoall_transpose(
            msa_mask[..., None].astype(jnp.int32), axis_name, row_sharded=True
        )[..., 0] > 0
        col_mask = jnp.swapaxes(mm, 1, 2).reshape(b * c_local, r_full)
    else:
        col_mask = None
    col_x = jnp.swapaxes(mc, 1, 2).reshape(b * c_local, r_full, d)
    col_out = attention_apply(params["attn_width"], attn_cfg, col_x, mask=col_mask)
    col_out = jnp.swapaxes(col_out.reshape(b, c_local, r_full, d), 1, 2)
    col_out = axial_alltoall_transpose(col_out, axis_name, row_sharded=False)

    return row_out + col_out


def _gathered_cross(params, cfg: Alphafold2Config, q_flat, ctx_local, q_mask, ctx_mask, axis_name):
    """pair<-MSA flat cross-attention: all_gather the (small) MSA context,
    attend locally over the resident pair-row queries."""
    cross_cfg = cfg.cross_attn_config()
    ctx = jax.lax.all_gather(ctx_local, axis_name, axis=1, tiled=True)  # (b, R, c, d)
    b = ctx.shape[0]
    ctx = ctx.reshape(b, -1, ctx.shape[-1])
    if ctx_mask is not None:
        cm = jax.lax.all_gather(
            ctx_mask.astype(jnp.int32), axis_name, axis=1, tiled=True
        ).reshape(b, -1) > 0
    else:
        cm = None
    out = attention_apply(
        params["attn"],
        cross_cfg,
        layer_norm(params["norm"], q_flat),
        context=layer_norm(params["norm_context"], ctx),
        mask=q_mask,
        context_mask=cm,
    )
    return out


def _ring_cross(params, cfg: Alphafold2Config, q_flat, ctx_flat_local, q_mask, ctx_mask_local, axis_name):
    """MSA<-pair flat cross-attention via ring K/V streaming.

    q_flat: (b, nq, d) resident queries; ctx_flat_local: (b, nk_local, d)
    the resident pair-token shard. K/V (and the key mask) rotate around the
    ring; the full pair stream never materializes on one chip. KV
    compression applies to the LOCAL shard before the ring (requires the
    local key length to be a multiple of the ratio so per-shard compression
    tiles the global one).
    """
    cross_cfg = cfg.cross_attn_config()
    h, dh = cross_cfg.heads, cross_cfg.dim_head
    qn = layer_norm(params["norm"], q_flat)
    cn = layer_norm(params["norm_context"], ctx_flat_local)
    dtype = cross_cfg.dtype

    q = _split_heads(linear(params["attn"]["to_q"], qn, dtype=dtype), h, dh)
    kv = linear(params["attn"]["to_kv"], cn, dtype=dtype)
    k, v = jnp.split(kv, 2, axis=-1)

    if cross_cfg.compress_ratio > 1:
        from alphafold2_tpu.ops.attention import _compress_kv

        if k.shape[1] % cross_cfg.compress_ratio != 0:
            raise ValueError(
                f"sequence-parallel KV compression needs the local key "
                f"length ({k.shape[1]}) divisible by the ratio "
                f"({cross_cfg.compress_ratio})"
            )
        k, v, ctx_mask_local = _compress_kv(
            params["attn"], cross_cfg, k, v, ctx_mask_local
        )
    k = _split_heads(k, h, dh)
    v = _split_heads(v, h, dh)

    out = ring_attention(q, k, v, axis_name, mask=ctx_mask_local)
    out = out.reshape(out.shape[0], out.shape[1], h * dh)
    del q_mask  # key-side masking only (ops/flash.py contract)
    return linear(params["attn"]["to_out"], out, dtype=dtype)


def _sp_layer(layer, cfg: Alphafold2Config, x, m, x_mask, msa_mask, axis_name):
    """One trunk layer on resident shards (deterministic path).

    x: (b, n_local, n, d) pair rows; m: (b, r_local, c, d) MSA rows.
    Mirrors models/trunk.py sequential order: pair self -> msa self ->
    pair<-msa cross -> msa<-pair cross -> FFs, every op residual.
    """
    from alphafold2_tpu.models.trunk import prenorm_ff_apply

    self_cfg = cfg.self_attn_config()
    b, n_local, n, d = x.shape

    x = x + sequence_parallel_axial_attention(
        layer["seq_attn"]["attn"],
        self_cfg,
        layer_norm(layer["seq_attn"]["norm"], x),
        axis_name,
        mask=x_mask,
    )

    if m is not None:
        m = m + _msa_self_attention(
            layer["msa_attn"]["attn"],
            cfg,
            layer_norm(layer["msa_attn"]["norm"], m),
            axis_name,
            msa_mask,
        )

        xf = x.reshape(b, n_local * n, d)
        xm_flat = x_mask.reshape(b, -1) if x_mask is not None else None
        mm_flat = msa_mask.reshape(b, -1) if msa_mask is not None else None
        xf = xf + _gathered_cross(
            layer["seq_cross"], cfg, xf, m, xm_flat, msa_mask, axis_name
        )
        x = xf.reshape(b, n_local, n, d)

        mf = m.reshape(b, -1, d)
        mf = mf + _ring_cross(
            layer["msa_cross"], cfg, mf, xf, mm_flat, xm_flat, axis_name
        )
        m = mf.reshape(m.shape)

    x = x + prenorm_ff_apply(layer["seq_ff"], cfg, x)
    if m is not None:
        m = m + prenorm_ff_apply(layer["msa_ff"], cfg, m)
    return x, m


def sp_trunk_apply(
    layers,
    cfg: Alphafold2Config,
    x,
    m,
    mesh: Mesh,
    *,
    axis_name: str = "seq",
    x_mask=None,
    msa_mask=None,
):
    """Run the sequential trunk sequence-parallel over `mesh[axis_name]`.

    Args (global, unsharded layouts — shard_map handles the split):
      x: (b, n, n, d) pair grid, rows sharded over axis_name;
      m: (b, rows, cols, d) MSA, rows sharded (rows % axis size == 0);
      masks as in models/trunk.py.

    Deterministic path only (dropout needs per-shard key plumbing; train
    with the replicated trunk or rng=None). cross_attn_mode="flat" only —
    the aligned mode's column folds are orthogonal to row sharding and run
    replicated (its memory already scales, see models/trunk.py).

    Returns (x, m) in global layouts.
    """
    if cfg.cross_attn_mode != "flat":
        raise ValueError("sp_trunk_apply implements cross_attn_mode='flat'")
    if any(cfg.layer_sparse):
        raise ValueError("sparse layers are not sequence-parallel; use the "
                         "replicated trunk")
    shards = mesh.shape[axis_name]
    if x.shape[1] % shards != 0:
        raise ValueError(
            f"pair-grid rows ({x.shape[1]}) must divide by the "
            f"'{axis_name}' mesh axis ({shards})"
        )
    if m is not None and m.shape[1] % shards != 0:
        raise ValueError(
            f"MSA rows ({m.shape[1]}) must divide by the "
            f"'{axis_name}' mesh axis ({shards})"
        )

    spec_x = P(None, axis_name)
    spec_m = P(None, axis_name)
    in_specs = (
        spec_x,
        spec_m if m is not None else None,
        spec_x if x_mask is not None else None,
        spec_m if msa_mask is not None else None,
    )

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(spec_x, spec_m if m is not None else None),
        check_vma=False,
    )
    def run(x, m, x_mask, msa_mask):
        for layer in layers:
            x, m = _sp_layer(layer, cfg, x, m, x_mask, msa_mask, axis_name)
        return x, m

    return run(x, m, x_mask, msa_mask)


def alphafold2_apply_sp(
    params,
    cfg: Alphafold2Config,
    seq,
    msa,
    mesh: Mesh,
    *,
    axis_name: str = "seq",
    mask=None,
    msa_mask=None,
    templates=None,
    templates_mask=None,
):
    """FULL-model forward with the trunk sequence-parallel over the mesh.

    Embeddings, the (optional) template tower, and the distogram head run
    replicated — they are a negligible share of the FLOPs and memory; the
    trunk (where the pair grid lives) runs under shard_map with its row
    axes sharded. Parity with the replicated `alphafold2_apply` is tested
    full-model on the 8-device mesh (tests/test_sp_trunk.py).

    Requires a token MSA (the embedds grid-stream substitute has no row
    axis to shard), the sequential trunk, and the sp_trunk_apply
    constraints (deterministic, flat cross-attention, no sparse layers).
    """
    from alphafold2_tpu.models.alphafold2 import alphafold2_apply

    if cfg.reversible:
        raise ValueError(
            "sequence-parallel trunk uses the sequential layer list; "
            "set reversible=False (memory scales via sharding instead)"
        )
    if msa is None:
        raise ValueError("alphafold2_apply_sp requires a token MSA")

    def trunk_fn(layers, cfg_, x, m, x_mask, m_mask, rng):
        del rng  # deterministic path (sp_trunk_apply contract)
        return sp_trunk_apply(
            layers, cfg_, x, m, mesh,
            axis_name=axis_name, x_mask=x_mask, msa_mask=m_mask,
        )

    return alphafold2_apply(
        params, cfg, seq, msa,
        mask=mask, msa_mask=msa_mask,
        templates=templates, templates_mask=templates_mask,
        trunk_fn=trunk_fn,
    )
