"""Sequence-parallel trunk: the full dual-track trunk under `shard_map`.

Round-1 shipped the SP primitives (parallel/sequence.py) but no model path
used them (VERDICT r1 missing #3). This module runs the REAL trunk layer —
pair axial self-attention, (tied-row) MSA axial self-attention, both flat
cross-attentions, feed-forwards — with the pair grid's ROW axis and the MSA
ROW axis sharded over one mesh axis, inside a single `shard_map`:

  * pair self-attention  -> `sequence_parallel_axial_attention`
    (row pass local, column pass via all_to_all grid transpose);
  * MSA self-attention   -> tied rows: `tied_row_attention_sharded`
    (logit psum over the row shards) for the along-columns pass + an
    all_to_all transpose for the along-rows pass; untied: the same
    axial primitive as the pair grid;
  * pair<-MSA cross      -> the MSA stream is small: one all_gather of the
    context, then local dense cross-attention over the resident pair rows
    (per column group when cross_attn_mode="aligned");
  * MSA<-pair cross      -> the pair stream is the big one: ring
    cross-attention — resident MSA queries stream the pair K/V shards
    around the ring (`ppermute`), nothing is ever gathered (per column
    group when "aligned");
  * feed-forwards, norms, residuals — elementwise, shard-local.

Semantics match the replicated sequential trunk (flat OR aligned
cross-attention, dropout off) to float tolerance; `tests/test_sp_trunk.py` asserts
full-model parity on the 8-device CPU mesh. KV compression for
cross-attention applies per shard with a ring halo exchange
(`_compress_kv_sharded`) that reproduces the global compression window
grid exactly for any local key length >= ratio-1 — shard counts need not
divide the compression ratio.

Reference anchor: the axial fold-into-batch pattern this shards is
reference alphafold2_pytorch/alphafold2.py:240-286; SURVEY.md §2.2 maps it
to exactly this decomposition.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from alphafold2_tpu import compat
from alphafold2_tpu.models.config import Alphafold2Config
from alphafold2_tpu.ops.attention import attention_apply
from alphafold2_tpu.ops.core import layer_norm, linear
from alphafold2_tpu.parallel.sequence import (
    axial_alltoall_transpose,
    ring_attention,
    sequence_parallel_axial_attention,
    tied_row_attention_sharded,
)


def _split_heads(t, heads, dim_head):
    b, n, _ = t.shape
    return t.reshape(b, n, heads, dim_head)


def _msa_self_attention(params, cfg: Alphafold2Config, m, axis_name, msa_mask):
    """MSA axial self-attention with the ROW axis sharded.

    m: (b, r_local, c, d). Two passes, summed (ops/attention.py
    axial_attention_apply semantics):
      * along-columns pass — tied over ALL rows via the sharded-logit psum
        when cfg.msa_tie_row_attn, else plain attention with rows folded;
      * along-rows pass — all_to_all transpose to column shards, attend
        over the full row axis, transpose back.
    """
    attn_cfg = cfg.self_attn_config()
    b, r_local, c, d = m.shape

    # along-columns pass (the reference's tied "row attention",
    # alphafold2.py:280-282)
    if cfg.msa_tie_row_attn:
        row_out = tied_row_attention_sharded(
            params["attn_height"], attn_cfg, m, axis_name, mask=msa_mask
        )
    else:
        row_x = m.reshape(b * r_local, c, d)
        row_mask = msa_mask.reshape(b * r_local, c) if msa_mask is not None else None
        row_out = attention_apply(
            params["attn_height"], attn_cfg, row_x, mask=row_mask
        ).reshape(b, r_local, c, d)

    # along-rows pass: flip the sharded axis rows -> cols, fold cols
    mc = axial_alltoall_transpose(m, axis_name, row_sharded=True)  # (b, R, c_loc, d)
    r_full, c_local = mc.shape[1], mc.shape[2]
    if msa_mask is not None:
        mm = axial_alltoall_transpose(
            msa_mask[..., None].astype(jnp.int32), axis_name, row_sharded=True
        )[..., 0] > 0
        col_mask = jnp.swapaxes(mm, 1, 2).reshape(b * c_local, r_full)
    else:
        col_mask = None
    col_x = jnp.swapaxes(mc, 1, 2).reshape(b * c_local, r_full, d)
    col_out = attention_apply(params["attn_width"], attn_cfg, col_x, mask=col_mask)
    col_out = jnp.swapaxes(col_out.reshape(b, c_local, r_full, d), 1, 2)
    col_out = axial_alltoall_transpose(col_out, axis_name, row_sharded=False)

    return row_out + col_out


def _gather_msa(m_local, msa_mask, axis_name):
    """all_gather the (small) MSA stream and its mask over the row shards:
    (b, r_local, c, d) -> (b, R, c, d)."""
    m_full = jax.lax.all_gather(m_local, axis_name, axis=1, tiled=True)
    mm_full = None
    if msa_mask is not None:
        mm_full = jax.lax.all_gather(
            msa_mask.astype(jnp.int32), axis_name, axis=1, tiled=True
        ) > 0
    return m_full, mm_full


def _gathered_cross(params, cfg: Alphafold2Config, q_flat, ctx_local, q_mask, ctx_mask, axis_name):
    """pair<-MSA flat cross-attention: all_gather the (small) MSA context,
    attend locally over the resident pair-row queries."""
    cross_cfg = cfg.cross_attn_config()
    ctx, cm_grid = _gather_msa(ctx_local, ctx_mask, axis_name)
    b = ctx.shape[0]
    ctx = ctx.reshape(b, -1, ctx.shape[-1])
    cm = cm_grid.reshape(b, -1) if cm_grid is not None else None
    out = attention_apply(
        params["attn"],
        cross_cfg,
        layer_norm(params["norm"], q_flat),
        context=layer_norm(params["norm_context"], ctx),
        mask=q_mask,
        context_mask=cm,
    )
    return out


def _compress_kv_sharded(params, cfg, k, v, context_mask, axis_name):
    """Per-shard KV compression EXACTLY matching the global strided conv.

    The global compression (ops/attention.py `_compress_kv`) convolves
    windows [0:r], [r:2r], ... of the full key sequence. Shard s holds the
    contiguous slice [s*L, (s+1)*L); when L is not a multiple of the ratio
    those windows straddle shard boundaries, which is why the old code
    required divisibility. Instead: each shard fetches a (ratio-1)-element
    halo from its right neighbor (`ppermute`; the last shard receives
    zeros — exactly the global path's zero padding), computes the
    ceil(L/ratio) candidate windows whose starts land in its slice, and
    masks off slots it does not own. Window starts within a shard are
    stride-`ratio` from `(-s*L) mod ratio`, so the owned windows are one
    dynamic slice + the same grouped conv as the dense path. The union of
    owned slots over shards is exactly the global window set, so ring
    attention over the compressed shards reproduces the replicated result
    to accumulation-order tolerance.

    k, v: (B, L, inner) local shard. Returns (k_c, v_c, slot_mask) with
    W = ceil(L/ratio) slots; slot_mask combines window ownership with the
    sum-pooled key mask (reference alphafold2.py:116-136 semantics).
    """
    ratio = cfg.compress_ratio
    B, L, _ = k.shape
    if L < ratio - 1:
        raise ValueError(
            f"sequence-parallel KV compression needs the local key length "
            f"({L}) >= ratio-1 ({ratio - 1}): a compression window may not "
            f"span more than two shards"
        )
    from alphafold2_tpu.ops.attention import _compress_conv

    num_shards = jax.lax.psum(1, axis_name)
    s = jax.lax.axis_index(axis_name)
    W = -(-L // ratio)  # ceil: max windows any shard can own
    halo_len = ratio - 1
    # shard s receives shard s+1's head; the LAST shard receives zeros
    # (ppermute default for unlisted destinations) == global zero padding
    perm = [(i, i - 1) for i in range(1, num_shards)]

    # ONE fused halo collective: k, v (and the key mask as one extra
    # feature column when present) ride a single ppermute — the halos are
    # tiny, so per-collective latency dominates
    fused = [k, v]
    if context_mask is not None:
        fused.append(context_mask.astype(k.dtype)[..., None])
    t = jnp.concatenate(fused, axis=-1)
    halo = jax.lax.ppermute(t[:, :halo_len], axis_name, perm)
    # slack so the W-window slice below always stays in bounds; only
    # un-owned (masked) slots ever read it, values are irrelevant
    slack = jnp.zeros((B, ratio + 1, t.shape[-1]), t.dtype)
    t_ext = jnp.concatenate([t, halo, slack], axis=1)

    # local offset of the first global window start inside this shard;
    # owned window starts are stride-`ratio` from it
    offset0 = (-(s * L)) % ratio
    t_win = jax.lax.dynamic_slice_in_dim(t_ext, offset0, W * ratio, axis=1)

    inner = k.shape[-1]
    k_c = _compress_conv(params, cfg, t_win[..., :inner])
    v_c = _compress_conv(params, cfg, t_win[..., inner:2 * inner])
    owned = (offset0 + jnp.arange(W) * ratio) < L
    if context_mask is None:
        # every owned window starts inside the shard, so it contains at
        # least one real key: ownership alone is the slot mask
        return k_c, v_c, jnp.broadcast_to(owned[None, :], (B, W))
    pooled = t_win[..., -1].reshape(B, W, ratio).sum(-1) > 0
    return k_c, v_c, pooled & owned[None, :]


def _ring_cross_tokens(params, cfg: Alphafold2Config, q_tokens, ctx_tokens_local, ctx_mask_local, axis_name, *, overlap=None):
    """Cross-attention with resident queries and ring-streamed K/V shards.

    q_tokens: (B, nq, d) resident queries; ctx_tokens_local: (B, nk_local, d)
    this chip's key/value token shard. K/V (and the key mask) rotate around
    the ring; the full key stream never materializes on one chip. KV
    compression applies per shard via `_compress_kv_sharded` (halo
    exchange reproduces the global window grid for ANY local length >=
    ratio-1; the halo ppermute stays synchronous — it is a tiny
    latency-bound prologue, not a per-hop transfer). Key-side masking
    only (ops/flash.py contract): query-side masks are intentionally not
    applied, like the dense path. `overlap` selects the ring schedule
    (parallel/sequence.py ring_attention; None = AF2_COMM_OVERLAP).
    """
    cross_cfg = cfg.cross_attn_config()
    h, dh = cross_cfg.heads, cross_cfg.dim_head
    qn = layer_norm(params["norm"], q_tokens)
    cn = layer_norm(params["norm_context"], ctx_tokens_local)
    dtype = cross_cfg.dtype

    q = _split_heads(linear(params["attn"]["to_q"], qn, dtype=dtype), h, dh)
    kv = linear(params["attn"]["to_kv"], cn, dtype=dtype)
    k, v = jnp.split(kv, 2, axis=-1)

    if cross_cfg.compress_ratio > 1:
        k, v, ctx_mask_local = _compress_kv_sharded(
            params["attn"], cross_cfg, k, v, ctx_mask_local, axis_name
        )
    k = _split_heads(k, h, dh)
    v = _split_heads(v, h, dh)

    out = ring_attention(q, k, v, axis_name, mask=ctx_mask_local,
                         overlap=overlap)
    out = out.reshape(out.shape[0], out.shape[1], h * dh)
    if cross_cfg.gate:
        # resident-query output gate: elementwise on this shard's rows, so
        # the ring schedule is untouched (ops/flash.py apply_output_gate)
        from alphafold2_tpu.ops.flash import apply_output_gate

        out = apply_output_gate(
            out, linear(params["attn"]["to_gate"], qn, dtype=dtype)
        )
    return linear(params["attn"]["to_out"], out, dtype=dtype)


def _ring_cross(params, cfg: Alphafold2Config, q_flat, ctx_flat_local, q_mask, ctx_mask_local, axis_name, *, overlap=None):
    """MSA<-pair flat cross-attention via ring K/V streaming."""
    del q_mask  # key-side masking only (ops/flash.py contract)
    return _ring_cross_tokens(
        params, cfg, q_flat, ctx_flat_local, ctx_mask_local, axis_name,
        overlap=overlap,
    )


def _fold_pair_local(x_local, c, x_mask_local=None):
    """Column-fold the LOCAL pair-row shard (models/trunk.py
    `_fold_by_msa_column` with the row axis restricted to this shard):
    (b, n_loc, n, d) -> (b*c, n_loc*f, d), grouped by which chunk of f grid
    columns maps to MSA column c. Queries/keys are per-position, so the
    shard-local fold is exactly the replicated fold's row-slice."""
    b, n_loc, n, d = x_local.shape
    if n % c != 0:
        raise ValueError(
            f"aligned cross-attention needs the pair side ({n}) divisible "
            f"by the MSA column count ({c})"
        )
    f = n // c
    xg = (
        x_local.reshape(b, n_loc, c, f, d)
        .transpose(0, 2, 1, 3, 4)
        .reshape(b * c, n_loc * f, d)
    )
    mg = None
    if x_mask_local is not None:
        mg = (
            x_mask_local.reshape(b, n_loc, c, f)
            .transpose(0, 2, 1, 3)
            .reshape(b * c, n_loc * f)
        )
    return xg, mg, f


def _aligned_gathered_cross(params, cfg: Alphafold2Config, x_local, m_local, x_mask, msa_mask, axis_name):
    """pair<-MSA ALIGNED cross-attention, rows sharded.

    Each pair token attends only its grid column's MSA column
    (models/trunk.py cross_apply_grids "aligned"). The MSA context is small,
    so it is all_gathered over the row shards; queries are the resident
    pair rows, column-folded locally. O(n_loc * n * r) per chip — the
    sharded version of the O(n^2 * r) redesign.
    """
    cross_cfg = cfg.cross_attn_config()
    b, n_loc, n, d = x_local.shape
    c = m_local.shape[2]

    m_full, mm_full = _gather_msa(m_local, msa_mask, axis_name)  # (b, R, c, d)
    r_full = m_full.shape[1]
    mg = jnp.swapaxes(m_full, 1, 2).reshape(b * c, r_full, d)
    mg_mask = (
        jnp.swapaxes(mm_full, 1, 2).reshape(b * c, r_full)
        if mm_full is not None
        else None
    )

    xg, xg_mask, f = _fold_pair_local(x_local, c, x_mask)
    out = attention_apply(
        params["attn"],
        cross_cfg,
        layer_norm(params["norm"], xg),
        context=layer_norm(params["norm_context"], mg),
        mask=xg_mask,
        context_mask=mg_mask,
    )
    return (
        out.reshape(b, c, n_loc, f, d)
        .transpose(0, 2, 1, 3, 4)
        .reshape(b, n_loc, n, d)
    )


def _aligned_ring_cross(params, cfg: Alphafold2Config, m_local, x_local, msa_mask, x_mask, axis_name, *, overlap=None):
    """MSA<-pair ALIGNED cross-attention, rows sharded.

    Each MSA token attends only its column's pair-grid block. Queries are
    the resident MSA rows (column-folded); each column group's pair keys
    are sharded over the row axis, so the K/V shards stream around the ring
    (`_ring_cross_tokens` per group) — the full pair stream never gathers.
    Key-side masking only; `msa_mask` (query side) is intentionally unused,
    like the flat twin.
    """
    del msa_mask  # key-side masking only (ops/flash.py contract)
    b, r_loc, c, d = m_local.shape

    mg = jnp.swapaxes(m_local, 1, 2).reshape(b * c, r_loc, d)
    xg, xg_mask, _ = _fold_pair_local(x_local, c, x_mask)

    out = _ring_cross_tokens(params, cfg, mg, xg, xg_mask, axis_name,
                             overlap=overlap)
    return jnp.swapaxes(out.reshape(b, c, r_loc, d), 1, 2)


def sp_layer_apply(layer, cfg: Alphafold2Config, x, m, x_mask, msa_mask, axis_name, *, overlap=None):
    """One trunk layer on resident shards (deterministic path).

    Public within the package: the pipeline trunk (parallel/pipeline.py)
    uses it as the per-stage body when composing PP x SP.

    x: (b, n_local, n, d) pair rows; m: (b, r_local, c, d) MSA rows.
    Mirrors models/trunk.py sequential order: pair self -> msa self ->
    pair<-msa cross -> msa<-pair cross -> FFs, every op residual.

    `overlap` selects the ring-cross-attention schedule (double-buffered
    vs synchronous hops, parallel/sequence.py ring_attention); None
    defaults to AF2_COMM_OVERLAP. The axial/tied collectives
    (all_to_all, logit psum) are single semantic barriers, not per-hop
    streams — there is nothing to double-buffer there.

    cfg.trunk_schedule threads through shard_map: under
    "branch_parallel" the two tracks' self-attentions — including their
    collectives (the pair grid's all_to_all transpose vs the MSA track's
    all_to_all / tied-logit psum) — are expressed as independent
    branches joined (models/trunk.py schedule_join) before the cross
    exchange, so the branches map onto DISJOINT mesh work: neither
    branch's collectives are ordered behind the other branch's compute,
    and the ICI can interleave them. Same math as serial (allclose;
    tests/test_trunk_schedule.py pins it).
    """
    from alphafold2_tpu.models.trunk import (
        prenorm_ff_apply,
        schedule_fork,
        schedule_join,
    )

    self_cfg = cfg.self_attn_config()
    b, n_local, n, d = x.shape
    branch_parallel = cfg.trunk_schedule == "branch_parallel" and m is not None

    x1 = x + sequence_parallel_axial_attention(
        layer["seq_attn"]["attn"],
        self_cfg,
        layer_norm(layer["seq_attn"]["norm"], x),
        axis_name,
        mask=x_mask,
    )

    if m is None:
        x = x1
    else:
        m1 = m + _msa_self_attention(
            layer["msa_attn"]["attn"],
            cfg,
            layer_norm(layer["msa_attn"]["norm"], m),
            axis_name,
            msa_mask,
        )
        if branch_parallel:
            x1, m1 = schedule_join(x1, m1)
        x, m = x1, m1

        if cfg.cross_attn_mode == "aligned":
            x = x + _aligned_gathered_cross(
                layer["seq_cross"], cfg, x, m, x_mask, msa_mask, axis_name
            )
            m = m + _aligned_ring_cross(
                layer["msa_cross"], cfg, m, x, msa_mask, x_mask, axis_name,
                overlap=overlap,
            )
        else:
            xf = x.reshape(b, n_local * n, d)
            xm_flat = x_mask.reshape(b, -1) if x_mask is not None else None
            mm_flat = msa_mask.reshape(b, -1) if msa_mask is not None else None
            xf = xf + _gathered_cross(
                layer["seq_cross"], cfg, xf, m, xm_flat, msa_mask, axis_name
            )
            x = xf.reshape(b, n_local, n, d)

            mf = m.reshape(b, -1, d)
            mf = mf + _ring_cross(
                layer["msa_cross"], cfg, mf, xf, mm_flat, xm_flat, axis_name,
                overlap=overlap,
            )
            m = mf.reshape(m.shape)

        if branch_parallel:
            # close the exchange region: the next layer's join scopes to
            # its own branches (models/trunk.py schedule_fork)
            x = schedule_fork(x)
            m = schedule_fork(m)

    x = x + prenorm_ff_apply(layer["seq_ff"], cfg, x)
    if m is not None:
        m = m + prenorm_ff_apply(layer["msa_ff"], cfg, m)
    return x, m


def msa_sharded_layer_apply(layer, cfg: Alphafold2Config, x, m, x_mask,
                            msa_mask, axis_name):
    """One trunk layer with ONLY the MSA row axis sharded (deterministic).

    The FastFold (arxiv 2203.00854) observation behind dynamic axial
    parallelism: shard whichever axis dominates residency. `sp_layer_apply`
    shards the SEQUENCE (pair-grid rows + MSA rows together) — the right
    cut when the O(L^2) pair grid is the problem. This twin shards the MSA
    ROW axis alone and keeps the pair grid fully resident: the right cut
    when a deep alignment (rows >> L) dominates and the pair grid still
    fits one chip — pair-side ops run replicated (identical on every
    shard), the MSA stream's memory and attention FLOPs divide by the
    shard count, and cross-attention needs one all_gather of the (by
    assumption small-L) per-shard MSA rows instead of any ring.

    x: (b, n, n, d) FULL pair grid (replicated); m: (b, r_local, c, d)
    resident MSA row shard. Math matches the replicated sequential layer
    per valid position (key-side masking differences do not arise — the
    cross ops here are the replicated ones, only the MSA self-attention
    goes through the sharded tied/transpose path)."""
    from alphafold2_tpu.models.trunk import (
        cross_apply_grids,
        prenorm_axial_apply,
        prenorm_ff_apply,
    )

    self_cfg = cfg.self_attn_config()
    x = prenorm_axial_apply(
        layer["seq_attn"], self_cfg, x, mask=x_mask,
    ) + x
    m = m + _msa_self_attention(
        layer["msa_attn"]["attn"], cfg,
        layer_norm(layer["msa_attn"]["norm"], m), axis_name, msa_mask,
    )
    # pair<-MSA cross: the MSA stream is the small one by schedule choice —
    # gather it whole, then run the REPLICATED cross (exact reference math)
    m_full, mm_full = _gather_msa(m, msa_mask, axis_name)
    x = cross_apply_grids(
        layer["seq_cross"], cfg, x, m_full, x_mask, mm_full, None,
        "pair_from_msa",
    ) + x
    # MSA<-pair cross: queries are the resident rows; the pair context is
    # fully resident, so this is the replicated cross on a row slice (the
    # column fold is row-count agnostic)
    m = cross_apply_grids(
        layer["msa_cross"], cfg, m, x, msa_mask, x_mask, None,
        "msa_from_pair",
    ) + m
    x = prenorm_ff_apply(layer["seq_ff"], cfg, x) + x
    m = prenorm_ff_apply(layer["msa_ff"], cfg, m) + m
    return x, m


def msa_sharded_trunk_apply(
    layers,
    cfg: Alphafold2Config,
    x,
    m,
    mesh: Mesh,
    *,
    axis_name: str = "seq",
    x_mask=None,
    msa_mask=None,
):
    """Run the sequential trunk with ONLY the MSA rows sharded.

    Args (global, unsharded layouts — shard_map splits the MSA rows):
      x: (b, n, n, d) pair grid, REPLICATED on every shard;
      m: (b, rows, cols, d) MSA, rows sharded (rows % axis size == 0;
         cols % axis size == 0 for the along-rows transpose pass).

    The "shard MSA rows" arm of the serving schedule choice
    (serving/sp_arm.py): per-chip MSA residency and MSA-attention FLOPs
    divide by the shard count while the pair grid stays whole — pair ops
    are replicated compute, bit-identical across shards. Deterministic
    path only; no sparse layers; requires an MSA stream (there is nothing
    to shard without one). Returns (x, m) in global layouts."""
    if any(cfg.layer_sparse):
        raise ValueError("sparse layers are not sequence-parallel; use the "
                         "replicated trunk")
    if m is None:
        raise ValueError(
            "msa_sharded_trunk_apply shards the MSA row axis; with no MSA "
            "stream there is nothing to shard — use the replicated trunk "
            "or sp_trunk_apply"
        )
    shards = mesh.shape[axis_name]
    if m.shape[1] % shards != 0:
        raise ValueError(
            f"MSA rows ({m.shape[1]}) must divide by the "
            f"'{axis_name}' mesh axis ({shards})"
        )
    if m.shape[2] % shards != 0:
        raise ValueError(
            f"MSA cols ({m.shape[2]}) must divide by the "
            f"'{axis_name}' mesh axis ({shards}) — the along-rows "
            f"attention pass transposes the sharded axis onto the columns"
        )

    spec_m = P(None, axis_name)
    in_specs = (
        P(),
        spec_m,
        P() if x_mask is not None else None,
        spec_m if msa_mask is not None else None,
    )

    @functools.partial(
        compat.shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(P(), spec_m),
        check_vma=False,
    )
    def run(x, m, x_mask, msa_mask):
        for layer in layers:
            x, m = msa_sharded_layer_apply(
                layer, cfg, x, m, x_mask, msa_mask, axis_name
            )
        return x, m

    return run(x, m, x_mask, msa_mask)


def sp_trunk_apply(
    layers,
    cfg: Alphafold2Config,
    x,
    m,
    mesh: Mesh,
    *,
    axis_name: str = "seq",
    x_mask=None,
    msa_mask=None,
    overlap=None,
):
    """Run the sequential trunk sequence-parallel over `mesh[axis_name]`.

    Args (global, unsharded layouts — shard_map handles the split):
      x: (b, n, n, d) pair grid, rows sharded over axis_name;
      m: (b, rows, cols, d) MSA, rows sharded (rows % axis size == 0);
      masks as in models/trunk.py.

    Deterministic path only (dropout needs per-shard key plumbing; train
    with the replicated trunk or rng=None). Both cross_attn_mode values are
    supported: "flat" (all_gather MSA / ring pair K/V over the whole
    streams) and "aligned" (the O(n^2 * r) column-aligned redesign — the
    mode the north-star workload uses — with the same gather/ring split
    applied per column group).

    `overlap` selects the ring cross-attention schedule (double-buffered
    when on; parallel/sequence.py ring_attention); None defaults to
    AF2_COMM_OVERLAP. Overlapped and synchronous schedules are
    exact-parity (tests/test_overlap.py pins the full trunk both ways).

    Returns (x, m) in global layouts.
    """
    if any(cfg.layer_sparse):
        raise ValueError("sparse layers are not sequence-parallel; use the "
                         "replicated trunk")
    shards = mesh.shape[axis_name]
    if cfg.cross_attn_mode == "aligned" and x.shape[1] != x.shape[2]:
        # same contract as the replicated fold (models/trunk.py
        # _fold_by_msa_column) — the local fold can't see the global row
        # count, so check here
        raise ValueError(
            f"aligned cross-attention needs a square pair grid; got "
            f"({x.shape[1]}, {x.shape[2]})"
        )
    if x.shape[1] % shards != 0:
        raise ValueError(
            f"pair-grid rows ({x.shape[1]}) must divide by the "
            f"'{axis_name}' mesh axis ({shards})"
        )
    if m is not None and m.shape[1] % shards != 0:
        raise ValueError(
            f"MSA rows ({m.shape[1]}) must divide by the "
            f"'{axis_name}' mesh axis ({shards})"
        )

    spec_x = P(None, axis_name)
    spec_m = P(None, axis_name)
    in_specs = (
        spec_x,
        spec_m if m is not None else None,
        spec_x if x_mask is not None else None,
        spec_m if msa_mask is not None else None,
    )

    @functools.partial(
        compat.shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(spec_x, spec_m if m is not None else None),
        check_vma=False,
    )
    def run(x, m, x_mask, msa_mask):
        for layer in layers:
            x, m = sp_layer_apply(
                layer, cfg, x, m, x_mask, msa_mask, axis_name, overlap=overlap
            )
        return x, m

    return run(x, m, x_mask, msa_mask)


def alphafold2_apply_sp(
    params,
    cfg: Alphafold2Config,
    seq,
    msa,
    mesh: Mesh,
    *,
    axis_name: str = "seq",
    mask=None,
    msa_mask=None,
    templates=None,
    templates_mask=None,
    overlap=None,
    schedule: str = "sp_seq",
):
    """FULL-model forward with the trunk sharded over the mesh.

    Embeddings, the (optional) template tower, and the distogram head run
    replicated — they are a negligible share of the FLOPs and memory; the
    trunk (where the pair grid lives) runs under shard_map with the
    chosen axis sharded. Parity with the replicated `alphafold2_apply` is
    tested full-model on the 8-device mesh (tests/test_sp_trunk.py).

    `schedule` is the dynamic-axial-parallelism cut (FastFold, arxiv
    2203.00854; serving/sp_arm.py picks it per length bucket):
      * "sp_seq" — shard the SEQUENCE: pair-grid rows + MSA rows over the
        mesh axis (`sp_trunk_apply`) — the long-sequence schedule, the
        O(L^2) pair grid divides by the shard count;
      * "sp_msa" — shard the MSA ROWS only (`msa_sharded_trunk_apply`) —
        the deep-alignment schedule, the pair grid stays whole.

    Works with a token MSA (rows sharded) or msa=None under "sp_seq"
    (pair-grid-only distogram pretraining — the MSA branch is skipped,
    reference alphafold2.py:311). The embedds path is unsupported (its
    substitute stream has no row axis to shard). Requires the sequential
    trunk and the per-schedule constraints (deterministic, no sparse
    layers).
    """
    from alphafold2_tpu.models.alphafold2 import alphafold2_apply

    if cfg.reversible:
        raise ValueError(
            "sequence-parallel trunk uses the sequential layer list; "
            "set reversible=False (memory scales via sharding instead)"
        )
    if schedule not in ("sp_seq", "sp_msa"):
        raise ValueError(
            f"schedule must be 'sp_seq' or 'sp_msa', got {schedule!r}"
        )

    def trunk_fn(layers, cfg_, x, m, x_mask, m_mask, rng):
        del rng  # deterministic path (sp_trunk_apply contract)
        if schedule == "sp_msa":
            return msa_sharded_trunk_apply(
                layers, cfg_, x, m, mesh,
                axis_name=axis_name, x_mask=x_mask, msa_mask=m_mask,
            )
        return sp_trunk_apply(
            layers, cfg_, x, m, mesh,
            axis_name=axis_name, x_mask=x_mask, msa_mask=m_mask,
            overlap=overlap,
        )

    return alphafold2_apply(
        params, cfg, seq, msa,
        mask=mask, msa_mask=msa_mask,
        templates=templates, templates_mask=templates_mask,
        trunk_fn=trunk_fn,
    )
