"""Communication–compute overlap layer: the shared knobs and gradient
bucketing that turn the multi-chip paths from parity-correct into
latency-hiding.

FastFold (arxiv 2203.00854) and ScaleFold (arxiv 2404.11068) both
attribute their largest AlphaFold2 training wins to exactly two moves:
overlapping collectives with compute and shrinking what sits on the
critical path. This module holds the framework-wide pieces of that story:

  * `overlap_enabled` — ONE resolution point for the overlap on/off knob.
    Every overlapped path (`ring_attention`'s double-buffered schedule,
    the DP-overlap train step) defaults to the environment
    (`AF2_COMM_OVERLAP`, default on) so A/B legs — the MULTICHIP dryrun's
    overlap pair, `scripts/bench_sweep.py`'s overlap legs — flip one env
    var in a subprocess instead of threading a flag through every layer.

  * gradient bucketing (`plan_buckets` / `flatten_buckets` /
    `unflatten_buckets`) — the param pytree has hundreds of small leaves
    (norm scales, biases); one psum per leaf would put hundreds of
    latency-bound collectives on the wire per microbatch. Buckets
    coalesce leaves (in pytree order, split on dtype boundaries and a
    size cap) into a few large 1-D arrays, so the overlapped DP step
    (`parallel/train.py make_dp_overlap_train_step`) issues a handful of
    bandwidth-bound all-reduces instead.

The overlapped *schedules* themselves live next to their synchronous
twins: ring attention in `parallel/sequence.py`, the DP-accumulating
step in `parallel/train.py` + `training/harness.py`. The verification
that the overlap structurally exists (collectives not fencing the dots)
is `analysis/overlap_lint.py`.
"""

from __future__ import annotations

import os
from typing import Any, List, Sequence, Tuple

import jax
import jax.numpy as jnp

OVERLAP_ENV = "AF2_COMM_OVERLAP"


def overlap_enabled(override=None) -> bool:
    """Resolve the overlap knob: an explicit True/False wins; None reads
    `AF2_COMM_OVERLAP` (default ON — "0"/"false"/"off" disable; parsed
    in ops/knobs.py, the one home for every AF2_* knob).

    Read at TRACE time: a jitted program bakes the schedule in, so A/B
    harnesses must set the env before tracing (the dryrun and sweep legs
    run each arm in its own subprocess, which guarantees it).
    """
    if override is not None:
        return bool(override)
    from alphafold2_tpu.ops.knobs import comm_overlap_enabled

    return comm_overlap_enabled()


# --- gradient bucketing -----------------------------------------------------

# Default bucket cap: 4M elements = 16 MiB in f32. Large enough that a
# handful of buckets covers the whole model (the psum count stays small),
# small enough that the FIRST bucket's psum can start while later
# microbatch compute still runs.
DEFAULT_BUCKET_ELEMS = 1 << 22


def plan_buckets(tree, bucket_elems: int = DEFAULT_BUCKET_ELEMS):
    """Greedy bucket plan over `tree`'s leaves (abstract or concrete).

    Walks leaves in pytree order, packing consecutive leaves into one
    bucket until the element cap; a dtype change always starts a new
    bucket (a bucket is ONE concatenated 1-D array, so it must be
    dtype-homogeneous). A single leaf larger than the cap gets its own
    bucket. Returns (treedef, buckets) where buckets is a tuple of
    tuples of leaf indices covering every leaf exactly once.
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    buckets: List[Tuple[int, ...]] = []
    cur: List[int] = []
    cur_dtype = None
    cur_n = 0
    for i, leaf in enumerate(leaves):
        if cur and (leaf.dtype != cur_dtype or cur_n + leaf.size > bucket_elems):
            buckets.append(tuple(cur))
            cur, cur_n = [], 0
        cur.append(i)
        cur_dtype = leaf.dtype
        cur_n += leaf.size
    if cur:
        buckets.append(tuple(cur))
    return treedef, tuple(buckets)


def flatten_buckets(tree, buckets: Sequence[Tuple[int, ...]]) -> List[Any]:
    """Concatenate `tree`'s leaves into one 1-D array per bucket (the
    wire layout the coalesced psums ride)."""
    leaves = jax.tree_util.tree_flatten(tree)[0]
    return [
        jnp.concatenate([leaves[i].ravel() for i in ix])
        if len(ix) > 1
        else leaves[ix[0]].ravel()
        for ix in buckets
    ]


def unflatten_buckets(flats, shapes_tree, treedef, buckets):
    """Inverse of `flatten_buckets`: split each bucket back into its
    leaves, using `shapes_tree` (a matching pytree of abstract/concrete
    leaves) for shapes and dtypes."""
    leaves = jax.tree_util.tree_flatten(shapes_tree)[0]
    out = [None] * len(leaves)
    for flat, ix in zip(flats, buckets):
        off = 0
        for i in ix:
            size = leaves[i].size
            out[i] = flat[off:off + size].reshape(leaves[i].shape).astype(
                leaves[i].dtype
            )
            off += size
    return jax.tree_util.tree_unflatten(treedef, out)
