"""Mesh-sharded training step.

The same train step as alphafold2_tpu/training/harness.py, compiled with
explicit in/out shardings over a device mesh. Nothing about the step
function changes — gradient all-reduce over the "data" axis and the
tensor-parallel collectives over "model" are inserted by XLA's partitioner
from the sharding annotations. This one function replaces the reference's
intended DeepSpeed/NCCL stack (reference training_scripts/deepspeed.py,
install_deepspeed.sh) end to end.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
from jax.sharding import Mesh

from alphafold2_tpu.training.harness import (
    TrainConfig,
    distogram_loss_fn,
    make_train_step,
    train_state_init,
)
from alphafold2_tpu.parallel.sharding import (
    batch_shardings,
    replicated,
    state_shardings,
)


def sharded_train_state_init(
    key,
    cfg,
    tcfg: TrainConfig,
    mesh: Mesh,
    *,
    tp: bool = True,
    state_init: Callable = train_state_init,
):
    """Init the train state directly into its sharded layout.

    Runs init under jit with out_shardings so large params materialize
    already distributed (no host-memory full copy). `state_init` defaults
    to the distogram-pretrain state; pass e.g.
    `training.e2e_train_state_init` (with cfg=E2EConfig) for the full
    structure workload.
    """
    shape = jax.eval_shape(lambda k: state_init(k, cfg, tcfg), key)
    shardings = state_shardings(mesh, shape, tp=tp)
    init = jax.jit(
        lambda k: state_init(k, cfg, tcfg), out_shardings=shardings
    )
    return init(key), shardings


def make_sharded_train_step(
    cfg,
    tcfg: TrainConfig,
    mesh: Mesh,
    example_batch,
    *,
    loss_fn: Callable = distogram_loss_fn,
    tp: bool = True,
    donate_state: bool = True,
    state_init: Callable = train_state_init,
):
    """Compile the train step with sharding annotations for `mesh`.

    Args:
      example_batch: a batch pytree (or ShapeDtypeStructs) with leading
        (grad_accum, per_step_batch, ...) axes; the batch axis is sharded
        over "data".

    Returns: (jitted_step, state_shardings_tree). The step signature is
      unchanged: (state, batch, rng) -> (state, metrics).
    """
    step = make_train_step(cfg, tcfg, loss_fn)
    state_shape = jax.eval_shape(
        lambda k: state_init(k, cfg, tcfg), jax.random.PRNGKey(0)
    )
    st_shardings = state_shardings(mesh, state_shape, tp=tp)
    b_shardings = batch_shardings(mesh, example_batch, microbatched=True)

    jitted = jax.jit(
        step,
        in_shardings=(st_shardings, b_shardings, replicated(mesh)),
        out_shardings=(st_shardings, replicated(mesh)),
        donate_argnums=(0,) if donate_state else (),
    )
    return jitted, st_shardings


def sp_distogram_loss_fn(mesh: Mesh, axis_name: str = "seq"):
    """Distogram loss with the trunk SEQUENCE-parallel over `mesh[axis_name]`.

    The training configuration the north-star workload actually runs in:
    per-step batch 1, the pair grid too big for one chip, so the grid (not
    the batch) is what shards. Params and optimizer state stay replicated;
    gradients of the shard_map trunk are globally correct through the
    collective transposes (psum/ppermute/all_to_all) — parity-tested in
    tests/test_sp_trunk.py. Deterministic path (rng unused: sp_trunk_apply
    contract).
    """
    from alphafold2_tpu.training.harness import make_distogram_loss_fn

    return make_distogram_loss_fn(sp_model_apply(mesh, axis_name))


def sp_model_apply(mesh: Mesh, axis_name: str = "seq"):
    """alphafold2_apply-signature adapter over the sequence-parallel
    trunk — the public hook for running any alphafold2_apply consumer
    (predict_structure, custom losses) with the trunk under shard_map."""
    from alphafold2_tpu.parallel.sp_trunk import alphafold2_apply_sp

    def apply_fn(params, cfg, seq, msa, *, mask=None, msa_mask=None,
                 embedds=None, rng=None):
        if embedds is not None:
            raise ValueError(
                "the embedds path has no row axis to shard; use the "
                "replicated model for embedds input"
            )
        if cfg.attn_dropout > 0.0 or cfg.ff_dropout > 0.0:
            # rng is silently dropped below (sp_trunk_apply is
            # deterministic); with dropout configured that would train a
            # silently-different model than the replicated path
            raise ValueError(
                "the sequence-parallel trunk is deterministic; set "
                "attn_dropout=0 and ff_dropout=0 (or train replicated)"
            )
        del rng  # deterministic path (sp_trunk_apply contract)
        return alphafold2_apply_sp(
            params, cfg, seq, msa, mesh,
            axis_name=axis_name, mask=mask, msa_mask=msa_mask,
        )

    return apply_fn


def sp_e2e_loss_fn(mesh: Mesh, axis_name: str = "seq"):
    """The FULL structure loss (distogram -> MDS -> sidechain -> refiner ->
    Kabsch RMSD) with the trunk sequence-parallel — the north-star
    multi-chip training configuration. Trunk runs under shard_map; the
    geometry pipeline and refiner run replicated (negligible share). The
    `mesh[axis_name]` size must divide the elongated pair side (3L) and
    the MSA row count.
    """
    from alphafold2_tpu.training.e2e import make_e2e_loss_fn

    return make_e2e_loss_fn(sp_model_apply(mesh, axis_name))


def make_sp_train_step(
    cfg,
    tcfg: TrainConfig,
    mesh: Mesh,
    *,
    axis_name: str = "seq",
    donate_state: bool = True,
    loss_fn: Optional[Callable] = None,
):
    """Jitted train step with the trunk sequence-parallel.

    loss_fn defaults to the distogram pretraining loss; pass
    `sp_e2e_loss_fn(mesh)` (with cfg=E2EConfig) for the full structure
    workload. The step signature matches make_train_step: (state, batch,
    rng) -> (state, metrics), batch leaves carrying (grad_accum, batch,
    ...) leading axes. The sequence length must satisfy the sp_trunk_apply
    divisibility constraints for `mesh[axis_name]`.
    """
    step = make_train_step(
        cfg, tcfg, loss_fn or sp_distogram_loss_fn(mesh, axis_name)
    )
    return jax.jit(step, donate_argnums=(0,) if donate_state else ())
