"""Mesh-sharded training step.

The same train step as alphafold2_tpu/training/harness.py, compiled with
explicit in/out shardings over a device mesh. Nothing about the step
function changes — gradient all-reduce over the "data" axis and the
tensor-parallel collectives over "model" are inserted by XLA's partitioner
from the sharding annotations. This one function replaces the reference's
intended DeepSpeed/NCCL stack (reference training_scripts/deepspeed.py,
install_deepspeed.sh) end to end.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from alphafold2_tpu import compat
from alphafold2_tpu.training.harness import (
    TrainConfig,
    distogram_loss_fn,
    make_axis_accum_train_step,
    make_train_step,
    train_state_init,
)
from alphafold2_tpu.parallel.overlap import overlap_enabled
from alphafold2_tpu.parallel.sharding import (
    batch_shardings,
    replicated,
    state_shardings,
)


def sharded_train_state_init(
    key,
    cfg,
    tcfg: TrainConfig,
    mesh: Mesh,
    *,
    tp: bool = True,
    state_init: Callable = train_state_init,
):
    """Init the train state directly into its sharded layout.

    Runs init under jit with out_shardings so large params materialize
    already distributed (no host-memory full copy). `state_init` defaults
    to the distogram-pretrain state; pass e.g.
    `training.e2e_train_state_init` (with cfg=E2EConfig) for the full
    structure workload.
    """
    shape = jax.eval_shape(lambda k: state_init(k, cfg, tcfg), key)
    shardings = state_shardings(mesh, shape, tp=tp)
    init = jax.jit(
        lambda k: state_init(k, cfg, tcfg), out_shardings=shardings
    )
    return init(key), shardings


def make_sharded_train_step(
    cfg,
    tcfg: TrainConfig,
    mesh: Mesh,
    example_batch,
    *,
    loss_fn: Callable = distogram_loss_fn,
    tp: bool = True,
    donate_state: bool = True,
    state_init: Callable = train_state_init,
):
    """Compile the train step with sharding annotations for `mesh`.

    Args:
      example_batch: a batch pytree (or ShapeDtypeStructs) with leading
        (grad_accum, per_step_batch, ...) axes; the batch axis is sharded
        over "data".

    Returns: (jitted_step, state_shardings_tree). The step signature is
      unchanged: (state, batch, rng) -> (state, metrics).
    """
    step = make_train_step(cfg, tcfg, loss_fn)
    state_shape = jax.eval_shape(
        lambda k: state_init(k, cfg, tcfg), jax.random.PRNGKey(0)
    )
    st_shardings = state_shardings(mesh, state_shape, tp=tp)
    b_shardings = batch_shardings(mesh, example_batch, microbatched=True)

    jitted = jax.jit(
        step,
        in_shardings=(st_shardings, b_shardings, replicated(mesh)),
        out_shardings=(st_shardings, replicated(mesh)),
        donate_argnums=(0,) if donate_state else (),
    )
    return jitted, st_shardings


def make_multihost_train_step(
    cfg,
    tcfg: TrainConfig,
    local_example_batch,
    *,
    axes=None,
    loss_fn: Callable = distogram_loss_fn,
    tp: bool = True,
    donate_state: bool = True,
    state_init: Callable = train_state_init,
    telemetry=None,
):
    """The process-spanning train step: DP(xTPxSP) over ALL processes.

    The single-process `make_sharded_train_step` already is the
    multi-host step — GSPMD neither knows nor cares that the mesh's
    devices live in N processes — so this builder only supplies the
    multi-host plumbing around it:

      * the mesh spans `jax.devices()` (every process's devices; `axes`
        defaults to pure DP over the GLOBAL device count, and must
        multiply to exactly that count — parallel/mesh.py refuses
        local-only silent fallbacks in multi-process runs);
      * `local_example_batch` is this PROCESS's shard, with leading
        (grad_accum, per_process_batch, ...) axes; the compiled step
        consumes the GLOBAL batch (per-process x process count), built
        each step by the returned `assemble` (training/data.py
        `assemble_global_batch` over
        `compat.make_array_from_process_local_data`);
      * params/optimizer state shard by the partition-rule registry
        (replicated for pure DP; "model"-axis rules under TP), identical
        on every process;
      * `telemetry` (optional telemetry.TrainTelemetry): the returned
        `assemble` accounts its wall time into the goodput ledger's
        "assembly" bucket — the host-to-device/global-batch cost is a
        named badput cause, not invisible step overhead (exclusive-time
        accounting keeps it correct even when assembly runs inside the
        step's own account, as the trainer CLIs' step wrappers do).

    Every process must call the returned step in lockstep with its own
    local shard (SPMD); metrics come back fully replicated, so
    `float(metrics["loss"])` is process-local and identical everywhere.

    Returns (jitted_step, state_shardings, assemble, mesh) where
    `assemble(local_batch)` -> global-batch pytree of jax.Arrays.
    """
    from alphafold2_tpu.parallel.mesh import make_mesh
    from alphafold2_tpu.training.data import assemble_global_batch

    if axes is None:
        axes = {"data": jax.device_count()}
    # no explicit devices=: the default path carries mesh.py's
    # multi-process exact-cover guard (a local-count-derived axes dict
    # must error, not silently build a one-host mesh)
    mesh = make_mesh(axes)
    procs = jax.process_count()

    def global_struct(x):
        if not hasattr(x, "ndim") or x.ndim <= 1:
            return x
        shape = list(x.shape)
        shape[1] = shape[1] * procs  # axis 1: the microbatched batch axis
        return jax.ShapeDtypeStruct(tuple(shape), x.dtype)

    example = jax.tree_util.tree_map(global_struct, local_example_batch)
    data_size = dict(axes).get("data", 0)
    global_b = next(
        (leaf.shape[1] for leaf in jax.tree_util.tree_leaves(example)
         if hasattr(leaf, "ndim") and leaf.ndim > 1),
        None,
    )
    if data_size and global_b is not None and global_b % data_size:
        raise ValueError(
            f"global batch {global_b} (= per-process x "
            f"{procs} processes) must be divisible by the mesh's data "
            f"axis size ({data_size} devices) — every chip gets an "
            "equal batch shard; raise the global batch or shrink the "
            "data axis"
        )
    step, st_shardings = make_sharded_train_step(
        cfg, tcfg, mesh, example,
        loss_fn=loss_fn, tp=tp, donate_state=donate_state,
        state_init=state_init,
    )

    if telemetry is None:
        from alphafold2_tpu.telemetry.goodput import NULL_TRAIN_TELEMETRY

        telemetry = NULL_TRAIN_TELEMETRY

    def assemble(local_batch):
        with telemetry.account("assembly"):
            return assemble_global_batch(local_batch, mesh,
                                         microbatched=True)

    return step, st_shardings, assemble, mesh


def make_dp_overlap_train_step(
    cfg,
    tcfg: TrainConfig,
    mesh: Mesh,
    example_batch,
    *,
    axis_name: str = "data",
    loss_fn: Callable = distogram_loss_fn,
    overlap=None,
    bucket_elems: Optional[int] = None,
    donate_state: bool = True,
    state_init: Callable = train_state_init,
):
    """The backward-overlapped data-parallel train step.

    Same signature family as `make_sharded_train_step`, but the step runs
    under `shard_map` over `mesh[axis_name]` with the gradient reduction
    placed EXPLICITLY (training/harness.py `make_axis_accum_train_step`):
    gradients coalesce into a few large buckets and, with overlap on
    (default: AF2_COMM_OVERLAP), the psum of microbatch i-1 is issued
    inside the scan body before microbatch i's forward/backward — the
    all-reduce rides the interconnect under compute instead of fencing
    the optimizer. `overlap=False` is the synchronous reference arm
    (one bucketed psum after the scan).

    Composition: params (and optimizer state) stay replicated — this is
    the pure-DP configuration, so `loss_fn` may be any shard_map-safe
    loss over the replicated model (the distogram default, the full
    `e2e_loss_fn` structure loss). The SP/PP steps keep their GSPMD jit
    wrappers and get THEIR overlap from the double-buffered ring
    schedules inside the trunk (parallel/sequence.py); DP-overlap x TP
    is not supported — a manual data axis precludes GSPMD auto-sharding
    of the model inside the same program (use `make_sharded_train_step`
    for DP+TP).

    Args:
      example_batch: a batch pytree (or ShapeDtypeStructs) with leading
        (grad_accum, global_per_step_batch, ...) axes; the per-step batch
        axis is sharded over `axis_name` and must divide by it.

    Returns: (jitted_step, state_shardings). The step signature is
    unchanged: (state, batch, rng) -> (state, metrics); donation-safe
    (state buffers are donated unless donate_state=False).
    """
    state_shape = jax.eval_shape(
        lambda k: state_init(k, cfg, tcfg), jax.random.PRNGKey(0)
    )
    step = make_axis_accum_train_step(
        cfg, tcfg, loss_fn, axis_name,
        overlap=overlap_enabled(overlap),
        bucket_elems=bucket_elems,
        state_init=state_init,
        state_shape=state_shape,
    )

    rep = PartitionSpec()
    st_specs = jax.tree_util.tree_map(lambda _: rep, state_shape)
    b_specs = jax.tree_util.tree_map(
        lambda _: PartitionSpec(None, axis_name), example_batch
    )
    sharded = compat.shard_map(
        step,
        mesh=mesh,
        in_specs=(st_specs, b_specs, rep),
        out_specs=(st_specs, rep),
        check_vma=False,
    )
    sharded_norng = compat.shard_map(
        lambda state, batch: step(state, batch, None),
        mesh=mesh,
        in_specs=(st_specs, b_specs),
        out_specs=(st_specs, rep),
        check_vma=False,
    )

    def step_with_optional_rng(state, batch, rng=None):
        # shard_map needs a concrete input pytree, so rng=None (the
        # deterministic path) dispatches to its own program at trace time
        if rng is None:
            return sharded_norng(state, batch)
        return sharded(state, batch, rng)

    jitted = jax.jit(
        step_with_optional_rng,
        donate_argnums=(0,) if donate_state else (),
    )
    st_shardings = jax.tree_util.tree_map(
        lambda _: NamedSharding(mesh, rep), state_shape
    )
    return jitted, st_shardings


def sp_distogram_loss_fn(mesh: Mesh, axis_name: str = "seq"):
    """Distogram loss with the trunk SEQUENCE-parallel over `mesh[axis_name]`.

    The training configuration the north-star workload actually runs in:
    per-step batch 1, the pair grid too big for one chip, so the grid (not
    the batch) is what shards. Params and optimizer state stay replicated;
    gradients of the shard_map trunk are globally correct through the
    collective transposes (psum/ppermute/all_to_all) — parity-tested in
    tests/test_sp_trunk.py. Deterministic path (rng unused: sp_trunk_apply
    contract).
    """
    from alphafold2_tpu.training.harness import make_distogram_loss_fn

    return make_distogram_loss_fn(sp_model_apply(mesh, axis_name))


def sp_model_apply(mesh: Mesh, axis_name: str = "seq"):
    """alphafold2_apply-signature adapter over the sequence-parallel
    trunk — the public hook for running any alphafold2_apply consumer
    (predict_structure, custom losses) with the trunk under shard_map."""
    from alphafold2_tpu.parallel.sp_trunk import alphafold2_apply_sp

    def apply_fn(params, cfg, seq, msa, *, mask=None, msa_mask=None,
                 embedds=None, templates=None, templates_mask=None,
                 rng=None):
        if embedds is not None:
            raise ValueError(
                "the embedds path has no row axis to shard; use the "
                "replicated model for embedds input"
            )
        if cfg.attn_dropout > 0.0 or cfg.ff_dropout > 0.0:
            # rng is silently dropped below (sp_trunk_apply is
            # deterministic); with dropout configured that would train a
            # silently-different model than the replicated path
            raise ValueError(
                "the sequence-parallel trunk is deterministic; set "
                "attn_dropout=0 and ff_dropout=0 (or train replicated)"
            )
        del rng  # deterministic path (sp_trunk_apply contract)
        return alphafold2_apply_sp(
            params, cfg, seq, msa, mesh,
            axis_name=axis_name, mask=mask, msa_mask=msa_mask,
            templates=templates, templates_mask=templates_mask,
        )

    return apply_fn


def sp_e2e_loss_fn(mesh: Mesh, axis_name: str = "seq"):
    """The FULL structure loss (distogram -> MDS -> sidechain -> refiner ->
    Kabsch RMSD) with the trunk sequence-parallel — the north-star
    multi-chip training configuration. Trunk runs under shard_map; the
    geometry pipeline and refiner run replicated (negligible share). The
    `mesh[axis_name]` size must divide the elongated pair side (3L) and
    the MSA row count.
    """
    from alphafold2_tpu.training.e2e import make_e2e_loss_fn

    return make_e2e_loss_fn(sp_model_apply(mesh, axis_name))


def pp_model_apply(mesh: Mesh, axis_name: str = "pipe", *,
                   seq_axis: str = None, microbatches: int = None):
    """alphafold2_apply-signature adapter over the PIPELINED trunk — the
    public hook for running any alphafold2_apply consumer with the trunk
    staged over `mesh[axis_name]` (optionally composed with sequence
    parallelism over `seq_axis`). The batch must divide into the
    microbatch count (the pipeline schedules over batch microbatches, so
    per-step batch >= stage count; contrast sp_model_apply, which shards
    the GRID and serves batch 1)."""
    from alphafold2_tpu.parallel.pipeline import alphafold2_apply_pp

    def apply_fn(params, cfg, seq, msa, *, mask=None, msa_mask=None,
                 embedds=None, rng=None):
        if cfg.attn_dropout > 0.0 or cfg.ff_dropout > 0.0:
            # rng is dropped below (pipeline_trunk_apply is
            # deterministic); with dropout configured that would train a
            # silently-different model than the replicated path
            raise ValueError(
                "the pipelined trunk is deterministic; set "
                "attn_dropout=0 and ff_dropout=0 (or train replicated)"
            )
        del rng  # deterministic path (pipeline_trunk_apply contract)
        return alphafold2_apply_pp(
            params, cfg, seq, msa, mesh,
            axis_name=axis_name, seq_axis=seq_axis,
            microbatches=microbatches, mask=mask, msa_mask=msa_mask,
            embedds=embedds,
        )

    return apply_fn


def pp_distogram_loss_fn(mesh: Mesh, axis_name: str = "pipe", *,
                         seq_axis: str = None, microbatches: int = None):
    """Distogram loss with the trunk PIPELINED over `mesh[axis_name]` —
    the depth-48 single-step alternative to the reversible trunk:
    activations stay O(batch/S) in flight and autodiff of the ring
    schedule yields the pipelined backward (gradient parity in
    tests/test_pipeline.py). For params + optimizer state at 1/S per
    stage, init with pp_train_state_init and pass its shardings to
    make_pp_train_step."""
    from alphafold2_tpu.training.harness import make_distogram_loss_fn

    return make_distogram_loss_fn(pp_model_apply(
        mesh, axis_name, seq_axis=seq_axis, microbatches=microbatches))


def pp_e2e_loss_fn(mesh: Mesh, axis_name: str = "pipe", *,
                   seq_axis: str = None, microbatches: int = None):
    """The FULL structure loss (distogram -> MDS -> sidechain -> refiner
    -> Kabsch RMSD) with the trunk pipelined (optionally PP x SP). The
    geometry pipeline and refiner run replicated (negligible share);
    requires reversible=False (the pipeline IS the memory strategy)."""
    from alphafold2_tpu.training.e2e import make_e2e_loss_fn

    return make_e2e_loss_fn(pp_model_apply(
        mesh, axis_name, seq_axis=seq_axis, microbatches=microbatches))


def pp_train_state_init(
    key,
    cfg,
    tcfg: TrainConfig,
    mesh: Mesh,
    *,
    axis_name: str = "pipe",
    state_init: Callable = train_state_init,
):
    """Init the train state with the trunk DEPTH-STACKED and sharded 1/S
    over the pipe axis — the layout that actually delivers the
    pipeline's persistent-memory promise.

    A plain `train_state_init` stores the trunk as a per-layer list,
    which replicates all params + Adam moments on every device (GSPMD
    cannot propagate the pipe sharding backward through the per-step
    jnp.stack). Here the trunk params restack to (depth, ...) leaves
    sharded `P(axis_name)` — each stage holds depth/S layers of params
    AND optimizer state — and `pipeline_trunk_apply` consumes the
    stacked layout directly, so no gather ever materializes. Returns
    (state, state_shardings); pass both to make_pp_train_step. Works
    for any `state_init` whose params tree keeps the trunk under a
    "trunk" key (distogram pretrain and the e2e state both do).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from alphafold2_tpu.models.reversible import stack_layers
    from alphafold2_tpu.training.harness import make_optimizer

    model_cfg = getattr(cfg, "model", cfg)
    if getattr(model_cfg, "reversible", False):
        # a reversible init stores the trunk ALREADY depth-stacked, so
        # restack below would iterate dict keys and die deep inside
        # jnp.stack — raise the same clear error as the apply path
        raise ValueError(
            "the pipeline trunk uses the sequential layer list; set "
            "reversible=False (activation memory scales O(batch/S) via "
            "the schedule instead)"
        )

    def init(k):
        state = state_init(k, cfg, tcfg)

        def restack(node):
            if isinstance(node, dict):
                return {
                    kk: (stack_layers(list(v)) if kk == "trunk"
                         else restack(v))
                    for kk, v in node.items()
                }
            return node

        params = restack(state["params"])
        opt = make_optimizer(tcfg)
        return {
            "params": params,
            "opt_state": opt.init(params),  # moments mirror the layout
            "step": state["step"],
        }

    shape = jax.eval_shape(init, key)

    def spec(path, leaf):
        in_trunk = any(getattr(p, "key", None) == "trunk" for p in path)
        if in_trunk and leaf.ndim >= 1:
            return NamedSharding(mesh, P(axis_name))
        return NamedSharding(mesh, P())

    shardings = jax.tree_util.tree_map_with_path(spec, shape)
    return jax.jit(init, out_shardings=shardings)(key), shardings


def make_pp_train_step(
    cfg,
    tcfg: TrainConfig,
    mesh: Mesh,
    *,
    axis_name: str = "pipe",
    seq_axis: str = None,
    microbatches: int = None,
    donate_state: bool = True,
    loss_fn: Optional[Callable] = None,
    state_shardings=None,
):
    """Jitted train step with the trunk pipelined over `mesh[axis_name]`.

    loss_fn defaults to the distogram pretraining loss; pass
    `pp_e2e_loss_fn(mesh, ...)` (with cfg=E2EConfig) for the full
    structure workload. Step signature matches make_train_step:
    (state, batch, rng) -> (state, metrics); the per-step batch must
    divide into `microbatches` (default: the stage count).

    Pass the shardings from pp_train_state_init to pin the stacked
    trunk state 1/S per stage end to end (without them the state — and
    Adam moments — stay replicated; the pipeline then shards only the
    in-flight compute)."""
    step = make_train_step(
        cfg, tcfg,
        loss_fn or pp_distogram_loss_fn(
            mesh, axis_name, seq_axis=seq_axis, microbatches=microbatches),
    )
    if loss_fn is not None and (seq_axis is not None
                                or microbatches is not None):
        # the schedule kwargs only feed the DEFAULT loss; silently
        # ignoring them alongside a custom loss_fn would train a
        # different pipeline schedule than the caller asked for
        raise ValueError(
            "seq_axis/microbatches only apply to the default loss_fn; "
            "build the custom loss with pp_e2e_loss_fn(mesh, "
            "seq_axis=..., microbatches=...) instead"
        )
    kwargs = {"donate_argnums": (0,) if donate_state else ()}
    if state_shardings is not None:
        kwargs["in_shardings"] = (state_shardings, replicated(mesh),
                                  replicated(mesh))
        kwargs["out_shardings"] = (state_shardings, replicated(mesh))
    return jax.jit(step, **kwargs)


def make_sp_train_step(
    cfg,
    tcfg: TrainConfig,
    mesh: Mesh,
    *,
    axis_name: str = "seq",
    donate_state: bool = True,
    loss_fn: Optional[Callable] = None,
):
    """Jitted train step with the trunk sequence-parallel.

    loss_fn defaults to the distogram pretraining loss; pass
    `sp_e2e_loss_fn(mesh)` (with cfg=E2EConfig) for the full structure
    workload. The step signature matches make_train_step: (state, batch,
    rng) -> (state, metrics), batch leaves carrying (grad_accum, batch,
    ...) leading axes. The sequence length must satisfy the sp_trunk_apply
    divisibility constraints for `mesh[axis_name]`.
    """
    step = make_train_step(
        cfg, tcfg, loss_fn or sp_distogram_loss_fn(mesh, axis_name)
    )
    return jax.jit(step, donate_argnums=(0,) if donate_state else ())
