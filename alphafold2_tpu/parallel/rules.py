"""Partition-rule registry: regex over named param paths -> PartitionSpec.

PR 10 replaces the hand-threaded suffix logic that used to live in
`parallel/sharding.py` (a chain of `if leaf_name == "w" and parent in
(...)` tests) with the `match_partition_rules` / `named_tree_map` pattern
every serious multi-host JAX trainer converges on (SNIPPETS [1]/[2]):
each rule is a regex over the leaf's slash-joined tree path, the first
match wins, and the matched PartitionSpec is rank-adapted to the leaf.

Why a registry instead of code:

  * ONE rule table applies uniformly to params, to the optimizer state
    (optax's mu/nu subtrees mirror the param tree, so `.../to_q/w`
    matches at `opt_state/1/0/mu/.../to_q/w` too), and to the reversible
    trunk's depth-stacked layout (a leaf whose rank is one above the
    rule's spec gets a leading replicated depth axis);
  * coverage is CHECKABLE: an unmatched non-scalar leaf raises loudly at
    sharding time, and `analysis/sharding_lint.py` cross-checks the
    registry against the live model tree chip-free via `eval_shape`
    (SHARD005/6/7) — a new param name added to the model cannot silently
    replicate multi-GB tensors on every chip of a pod;
  * the rules are DATA, so the lint validates every axis name against
    `parallel/mesh.py` KNOWN_AXES without tracing anything.

Tensor-parallel layout encoded below (the Megatron split, as GSPMD
annotations — XLA inserts the collectives):

  * attention to_q / to_kv weights shard their OUTPUT (head) dim;
  * attention to_out weight shards its INPUT dim (XLA adds the psum);
  * feed-forward proj_in shards output, proj_out shards input;
  * the KV-compression conv shards its output channels (per-head groups);
  * embeddings, norms, output heads, biases of row-sharded layers:
    replicated.
"""

from __future__ import annotations

import re
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

Rule = Tuple[str, P]


def tree_path_string(path, sep: str = "/") -> str:
    """Slash-joined name of one pytree path (SNIPPETS [2]'s
    `tree_path_to_string`): dict keys, sequence indices, and attr names
    each become one segment, so `params/trunk/0/attn/to_q/w` names the
    same leaf in the param tree and (suffix-wise) in optax's mirrors."""
    keys: List[str] = []
    for e in path:
        if isinstance(e, jax.tree_util.DictKey):
            keys.append(str(e.key))
        elif isinstance(e, jax.tree_util.SequenceKey):
            keys.append(str(e.idx))
        elif isinstance(e, jax.tree_util.GetAttrKey):
            keys.append(str(e.name))
        elif isinstance(e, jax.tree_util.FlattenedIndexKey):
            keys.append(str(e.key))
        else:
            keys.append(str(e))
    return sep.join(keys)


def named_tree_map(f: Callable[[str, Any], Any], tree: Any, *, sep: str = "/",
                   is_leaf=None) -> Any:
    """`tree_map` whose function also receives the leaf's joined path name
    — the substrate `match_partition_rules` runs on (SNIPPETS [1])."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: f(tree_path_string(path, sep), leaf),
        tree,
        is_leaf=is_leaf,
    )


# --- the registry -----------------------------------------------------------

#: Tensor-parallel rules over the "model" mesh axis. First match wins;
#: specs are written at the leaf's BASE rank (no depth stacking) and
#: rank-adapt automatically (see `spec_for_leaf`). The trailing
#: name-anchored replicate rules are the EXPLICIT coverage closure: every
#: parameter family this model can produce is named, so a leaf outside
#: the vocabulary is an unmatched-leaf error, not a silent replicate.
TP_RULES: Tuple[Rule, ...] = (
    # column-parallel: shard the output (head / FF-inner) dim
    (r"(^|/)(to_q|to_kv|proj_in)/w$", P(None, "model")),  # af2lint: rank=2
    (r"(^|/)(to_q|to_kv|proj_in)/b$", P("model")),  # af2lint: rank=1
    # row-parallel: shard the input dim (XLA inserts the psum)
    (r"(^|/)(to_out|proj_out)/w$", P("model", None)),  # af2lint: rank=2
    # KV-compression conv kernel (k, in_per_group, out) / bias (out,)
    (r"(^|/)compress/w$", P(None, None, "model")),  # af2lint: rank=3
    (r"(^|/)compress/b$", P("model")),  # af2lint: rank=1
    # everything else in the parameter vocabulary stays replicated:
    # remaining dense weights/biases (output heads, embedd projections),
    # embedding tables, norm scale/bias, and the int8-PTQ qw/scale pairs
    (r"(^|/)(w|b|table|scale|bias|qw)$", P()),
)

#: Fully-replicated registry (tp=False / meshes without a "model" axis).
REPLICATED_RULES: Tuple[Rule, ...] = ((r".", P()),)


def partition_rules(tp: bool = True) -> Tuple[Rule, ...]:
    """The default registry for a train state: TP_RULES when the mesh has
    a "model" axis to shard over, else everything replicated."""
    return TP_RULES if tp else REPLICATED_RULES


def rule_axes(rules: Sequence[Rule]) -> set:
    """Every mesh-axis name appearing in a rule set (for KNOWN_AXES
    validation — analysis/sharding_lint.py SHARD005)."""
    axes: set = set()
    for _pattern, spec in rules:
        for entry in spec:
            if entry is None:
                continue
            if isinstance(entry, (tuple, list)):
                axes.update(entry)
            else:
                axes.add(entry)
    return axes


def _is_scalar(leaf) -> bool:
    shape = getattr(leaf, "shape", None)
    if shape is None:
        return True  # non-array leaf (None, python scalar): replicate
    return len(shape) == 0 or int(np.prod(shape)) == 1


def spec_for_leaf(name: str, leaf, rules: Sequence[Rule]) -> Optional[P]:
    """First-match spec for one leaf, rank-adapted; None when no rule
    matches a non-scalar leaf (the caller decides whether that raises).

    Rank adaptation: a spec with k entries applies verbatim to a rank-k
    leaf; a rank-(k+1) leaf is the depth-stacked layout (the reversible
    trunk stores per-layer params under a leading depth axis) and gets
    `P(None, *spec)` — the depth axis is replicated, the base sharding
    shifts right. Any other rank mismatch on a SHARDED spec is an error:
    the rule matched something it was not written for.
    """
    if _is_scalar(leaf):
        return P()  # scalars never partition (optimizer counts, step)
    for pattern, spec in rules:
        if re.search(pattern, name) is None:
            continue
        k = len(spec)
        if k == 0:
            return P()  # replicated at any rank
        ndim = len(leaf.shape)
        if ndim == k:
            return spec
        if ndim == k + 1:
            return P(None, *spec)  # depth-stacked: leading axis replicated
        raise ValueError(
            f"partition rule {pattern!r} matched {name!r} but its spec "
            f"{spec} is written for rank {k} (or depth-stacked rank "
            f"{k + 1}) and the leaf has rank {ndim} — fix the rule or "
            "the parameter layout"
        )
    return None


def match_partition_rules(rules: Sequence[Rule], tree: Any, *,
                          sep: str = "/") -> Any:
    """PartitionSpec pytree for `tree` from first-match regex rules.

    Scalar (and non-array) leaves always replicate without consulting the
    rules. A non-scalar leaf no rule matches raises loudly — on a pod,
    a silently-replicated tensor costs HBM on every chip and a silently
    mis-sharded one corrupts the step; neither should survive to runtime.
    """

    def get_spec(name: str, leaf) -> P:
        spec = spec_for_leaf(name, leaf, rules)
        if spec is None:
            raise ValueError(
                f"no partition rule matched {name!r} "
                f"(shape {tuple(leaf.shape)}) — add a rule to the "
                "registry (alphafold2_tpu/parallel/rules.py); unmatched "
                "non-scalar leaves do not silently replicate"
            )
        return spec

    return named_tree_map(get_spec, tree, sep=sep)


def unmatched_leaves(rules: Sequence[Rule], tree: Any, *,
                     sep: str = "/") -> List[Tuple[str, tuple]]:
    """(name, shape) of every non-scalar leaf no rule matches — the
    chip-free coverage probe the sharding lint runs over `eval_shape`d
    model/train-state trees (and tests run over fixtures)."""
    missing: List[Tuple[str, tuple]] = []

    def probe(name: str, leaf):
        try:
            spec = spec_for_leaf(name, leaf, rules)
        except ValueError:
            spec = None  # rank-incompatible match counts as uncovered
        if spec is None:
            missing.append((name, tuple(getattr(leaf, "shape", ()))))
        return None

    named_tree_map(probe, tree, sep=sep)
    return missing
