"""Pipeline parallelism: the trunk staged over a mesh axis.

The last absent row of SURVEY.md §2.2 ("optional: stage the depth-48 trunk
across pods"). GPipe-style schedule, TPU-native mechanics: the depth-stacked
layer parameters are SHARDED over the "pipe" mesh axis (each device owns
depth/S consecutive layers), microbatches stream through the stages, and
ALL communication is neighbor `ppermute` of one microbatch per tick —
exactly the collective the hardware's ring likes. Everything runs inside
one `shard_map` + `lax.scan` over ticks; no host round-trips.

Schedule (S stages, M microbatches, T = M + S - 1 ticks):

  tick t: stage 0 ingests microbatch t; every stage applies its layer
          block to its resident activation; activations ppermute stage
          s -> s+1; the last stage finishes microbatch t - (S-1).

Activation memory is O(batch/S) per stage — inputs, outputs, AND
in-flight state (this is the reason to pipeline depth 48):

  * the input stack is sharded round-robin (microbatch i lives on stage
    i mod S, slot i//S) and DRIPS to stage 0 through a rotating ring
    register: during consumption cycle k (ticks kS..kS+S-1) slot k
    rotates one hop toward stage 0 per tick, so microbatch kS+j — parked
    j hops away — arrives exactly at tick kS+j. One extra
    microbatch-sized ppermute per tick, no gathered buffer.
  * finished microbatches ride a second ring register from the last
    stage back to their round-robin home (microbatch d enters at stage
    S-1 on tick d+S-1 and is harvested (d+1) mod S hops later at stage
    d mod S, slot d//S). The register carries its payload's microbatch
    index; an index of -1 marks garbage. A payload is overwritten at
    stage S-1 only after a full ring lap, which is strictly after its
    harvest hop — no collision.

Bubble fraction is (S-1)/T — the standard GPipe cost; pick M >= 4*S to
amortize. Parity vs the replicated sequential trunk and the O(batch/S)
buffer bound are tested on the 8-device CPU mesh (tests/test_pipeline.py).

TRAINABLE: autodiff through the schedule is exact — ppermute transposes
to the reverse permutation and the tick scan to the reverse-order scan,
so jax.grad yields a correctly pipelined BACKWARD schedule for free
(gradient parity vs the sequential trunk pinned in tests, both plain
and composed with SP).

The per-stage body is the REAL trunk layer (models/trunk.py
`trunk_layer_apply`, deterministic path): pair axial self-attn, MSA axial
self-attn (tied rows allowed — rows are NOT sharded here, so no psum is
needed), cross-attention (flat or aligned), feed-forwards. Interleaved
block-sparse layers (reference BASELINE config 3) are supported: the
sparse flag rides as per-stage DATA (an SPMD stage program cannot branch
on the stage index in Python), with `lax.cond` selecting the sparse or
dense pair self-attention body per scanned layer.

Per-stage parameter and optimizer state is 1/S of the trunk; pass
`seq_axis` to compose with the SP trunk (parallel/sp_trunk.py) on an
inner mesh axis when a single microbatch's pair grid itself outgrows a
chip: the stage body becomes the sequence-parallel layer (row-sharded
activations, all_to_all/psum/ring collectives over `seq_axis`) while the
three pipe rings keep ppermuting over `axis_name` — one shard_map over
both axes, no host coordination (tests/test_pipeline.py pins parity on a
2x4 pipe x seq CPU mesh).

Masks: batch-broadcast masks (shape (1, ...)) are tiled once and closed
over — zero ring cost. PER-EXAMPLE masks (shape (b, ...) — what padded
variable-length batches produce, reference alphafold2.py:156-161) travel
WITH their microbatches: round-robin sharded like the inputs, dripped to
stage 0 on the feed ring, and ppermuted stage-to-stage alongside the
activations they mask (they skip the return ring — masks are not
outputs).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from alphafold2_tpu import compat
from alphafold2_tpu.models.config import Alphafold2Config
from alphafold2_tpu.models.reversible import stack_layers
from alphafold2_tpu.models.trunk import trunk_layer_apply
from alphafold2_tpu.parallel.sp_trunk import sp_layer_apply


def _round_robin(t, M, S):
    """(M, mb, ...) -> (S, M/S, mb, ...): microbatch i to [i % S, i // S]."""
    return jnp.swapaxes(t.reshape((M // S, S) + t.shape[1:]), 0, 1)


def _un_round_robin(t, M):
    """(S, M/S, mb, ...) -> (M, mb, ...), inverse of `_round_robin`."""
    return jnp.swapaxes(t, 0, 1).reshape((M,) + t.shape[2:])


def pipeline_trunk_apply(
    layers,
    cfg: Alphafold2Config,
    x,
    m,
    mesh: Mesh,
    *,
    axis_name: str = "pipe",
    microbatches: int = None,
    x_mask=None,
    msa_mask=None,
    seq_axis: str = None,
):
    """Run the sequential trunk pipelined over `mesh[axis_name]`.

    Args (global layouts):
      layers: list of trunk_layer_init params (depth % stages == 0);
      x: (b, n, n, d) pair grid; m: (b, rows, cols, d) MSA or None;
      microbatches: how many microbatches to split b into (default =
        stage count; b % microbatches == 0 and microbatches % stages == 0
        — the round-robin input/output sharding needs whole slots);
      seq_axis: optional second mesh axis for PP x SP composition — the
        stage body becomes the sequence-parallel layer (sp_trunk.py
        sp_layer_apply) with the pair-grid row axis and MSA row axis
        sharded over it.

    Deterministic path only. Masks may be batch-broadcast (shape (1, ...),
    tiled once, zero ring cost) or PER-EXAMPLE (shape (b, ...), as padded
    variable-length batches produce): per-example masks travel with their
    microbatches on the feed/forward rings.

    Returns (x, m) in global layouts, numerically identical to
    sequential_trunk_apply with the same layers.
    """
    stages = mesh.shape[axis_name]
    if isinstance(layers, (list, tuple)):
        depth = len(layers)
        stacked = stack_layers(list(layers))  # (depth, ...) leaves
    else:
        # pre-stacked (depth, ...) pytree — the layout
        # pp_train_state_init stores so the persistent params/optimizer
        # state live sharded 1/S over the pipe axis (a per-step
        # jnp.stack of replicated layer lists would defeat that)
        stacked = layers
        depth = jax.tree_util.tree_leaves(stacked)[0].shape[0]
    if depth % stages != 0:
        raise ValueError(f"depth {depth} must divide into {stages} stages")
    # interleaved block-sparse layers (reference BASELINE config 3): the
    # SPMD stage body must be one program for every stage, so the sparse
    # flag becomes DATA — a per-stage flag vector scanned with the layer
    # params, lax.cond selecting the sparse or dense pair self-attention
    # body per layer. SP composition keeps the rejection (the sparse
    # layout is defined over the full row axis; sp_trunk_apply has the
    # same contract).
    sparse_flags = tuple(cfg.layer_sparse)
    has_sparse = any(sparse_flags)
    if has_sparse and len(sparse_flags) != depth:
        # validate BEFORE any use: a silent [:depth] slice could flip
        # which layers are sparse (or reject/dual-compile spuriously)
        raise ValueError(
            f"layer_sparse length {len(sparse_flags)} != depth {depth}"
        )
    if has_sparse and seq_axis:
        raise ValueError(
            "sparse layers are not sequence-parallel (the block layout "
            "spans the full row axis); use seq_axis=None"
        )
    seq_shards = mesh.shape[seq_axis] if seq_axis else 1
    if seq_axis:
        # same contracts as sp_trunk_apply, checked at the global layouts
        if cfg.cross_attn_mode == "aligned" and x.shape[1] != x.shape[2]:
            raise ValueError(
                f"aligned cross-attention needs a square pair grid; got "
                f"({x.shape[1]}, {x.shape[2]})"
            )
        if x.shape[1] % seq_shards != 0:
            raise ValueError(
                f"pair-grid rows ({x.shape[1]}) must divide by the "
                f"'{seq_axis}' mesh axis ({seq_shards})"
            )
        if m is not None and m.shape[1] % seq_shards != 0:
            raise ValueError(
                f"MSA rows ({m.shape[1]}) must divide by the "
                f"'{seq_axis}' mesh axis ({seq_shards})"
            )

    b = x.shape[0]
    M = microbatches or stages
    if b % M != 0:
        raise ValueError(f"batch {b} must divide into {M} microbatches")
    if M % stages != 0:
        raise ValueError(
            f"microbatches ({M}) must divide by the stage count ({stages}) "
            f"for the round-robin input/output sharding"
        )
    mb = b // M

    def classify_mask(mask, what):
        """-> (value, mode): 'none' | 'static' (tiled to mb once) |
        'travel' (round-robin stack riding the rings)."""
        if mask is None:
            return None, "none"
        if mask.shape[0] == 1:
            return jnp.tile(mask, (mb,) + (1,) * (mask.ndim - 1)), "static"
        if mask.shape[0] != b:
            raise ValueError(
                f"{what} batch dim {mask.shape[0]} must be 1 (broadcast) "
                f"or {b} (per-example)"
            )
        return (
            _round_robin(mask.reshape((M, mb) + mask.shape[1:]), M, stages),
            "travel",
        )

    x_mask_v, x_mask_mode = classify_mask(x_mask, "x_mask")
    msa_mask_v, msa_mask_mode = classify_mask(msa_mask, "msa_mask")

    has_msa = m is not None
    per_stage = depth // stages
    ticks = M + stages - 1
    slots = M // stages

    # round-robin-sharded microbatch stacks: (S, M/S, mb, ...)
    xs = _round_robin(x.reshape((M, mb) + x.shape[1:]), M, stages)
    ms = (
        _round_robin(m.reshape((M, mb) + m.shape[1:]), M, stages)
        if has_msa
        else None
    )

    def reshape_stage(t):
        # (depth, ...) -> (stages, per_stage, ...): shard leading axis
        return t.reshape((stages, per_stage) + t.shape[1:])

    stage_params = jax.tree_util.tree_map(reshape_stage, stacked)
    sparse_fn = None
    stage_flags = None
    if has_sparse:
        from alphafold2_tpu.models.trunk import make_sparse_axial_fn

        sparse_fn = make_sparse_axial_fn(cfg)
        stage_flags = jnp.asarray(sparse_flags, bool).reshape(
            stages, per_stage)

    def seq_sharded(spec_prefix, row_axis_pos):
        """PartitionSpec with the row axis additionally sharded over
        seq_axis (activation/mask row axes live after the stack dims)."""
        if not seq_axis:
            return P(*spec_prefix)
        pad = (None,) * (row_axis_pos - len(spec_prefix))
        return P(*spec_prefix, *pad, seq_axis)

    # activation stacks (S, M/S, mb, ROWS, ...): rows at index 3
    act_spec = seq_sharded((axis_name,), 3)
    # static masks (mb, ROWS, ...): rows at index 1 — P() (replicated)
    # without seq_axis, row-sharded with it; travel stacks ride like acts
    mask_spec = {
        "none": None,
        "static": seq_sharded((), 1),
        "travel": act_spec,
    }

    in_specs = (
        jax.tree_util.tree_map(lambda _: P(axis_name), stage_params),
        act_spec,  # each stage holds only its M/S input slots
        act_spec if has_msa else None,
        mask_spec[x_mask_mode],
        mask_spec[msa_mask_mode],
        P(axis_name) if has_sparse else None,
    )
    out_specs = (act_spec, act_spec if has_msa else None)

    @functools.partial(
        compat.shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=False,
    )
    def run(sp, xs, ms, xmk, mmk, sflags):
        # sp leaves: (1, per_stage, ...); xs: (1, M/S, mb, ...)
        my_layers = jax.tree_util.tree_map(lambda t: t[0], sp)
        xs = xs[0]
        ms = ms[0] if has_msa else None
        my_flags = sflags[0] if has_sparse else None  # (per_stage,)
        # mask shard_map args: travel stacks carry the sharded stage axis;
        # static args arrive replicated (or at local row shards under
        # seq_axis), ready to use
        xmk = xmk[0] if x_mask_mode == "travel" else xmk
        mmk = mmk[0] if msa_mask_mode == "travel" else mmk
        stage = jax.lax.axis_index(axis_name)
        is_first = stage == 0
        is_last = stage == stages - 1
        fwd_perm = [(s, (s + 1) % stages) for s in range(stages)]
        back_perm = [(s, (s - 1) % stages) for s in range(stages)]

        x_mask_const = xmk if x_mask_mode == "static" else None
        msa_mask_const = mmk if msa_mask_mode == "static" else None

        def apply_block(x_act, m_act, x_mk, m_mk):
            xm = x_mk if x_mask_mode == "travel" else x_mask_const
            mm = m_mk if msa_mask_mode == "travel" else msa_mask_const

            def body(carry, scanned):
                cx, cm = carry
                if has_sparse:
                    lp, flag = scanned
                    # the flag is data (stages differ), so both bodies
                    # compile and lax.cond selects per layer at runtime
                    cx, cm = jax.lax.cond(
                        flag,
                        lambda: trunk_layer_apply(
                            lp, cfg, cx, cm, x_mask=xm, msa_mask=mm,
                            sparse_fn=sparse_fn,
                        ),
                        lambda: trunk_layer_apply(
                            lp, cfg, cx, cm, x_mask=xm, msa_mask=mm
                        ),
                    )
                elif seq_axis:
                    cx, cm = sp_layer_apply(
                        scanned, cfg, cx, cm, xm, mm, seq_axis
                    )
                else:
                    cx, cm = trunk_layer_apply(
                        scanned, cfg, cx, cm, x_mask=xm, msa_mask=mm
                    )
                return (cx, cm), None

            (x_act, m_act), _ = jax.lax.scan(
                body, (x_act, m_act),
                (my_layers, my_flags) if has_sparse else my_layers,
            )
            return x_act, m_act

        def zeros_like_mb(t):
            return jnp.zeros((mb,) + t.shape[2:], t.dtype)

        x0, m0 = zeros_like_mb(xs), zeros_like_mb(ms) if has_msa else None
        # traveling-mask ring registers (garbage until the first real
        # microbatch's mask arrives — garbage ticks' outputs are never
        # harvested, so an all-False mask is harmless)
        xmk0 = zeros_like_mb(xmk) if x_mask_mode == "travel" else None
        mmk0 = zeros_like_mb(mmk) if msa_mask_mode == "travel" else None
        out_x = jnp.zeros_like(xs)
        out_m = jnp.zeros_like(ms) if has_msa else None
        # return-ring register: payload + the microbatch index it carries
        # (-1 = garbage). Starts empty.
        reg_idx0 = jnp.int32(-1)

        def harvest(out_x, out_m, reg_x, reg_m, reg_idx):
            """Write the return ring's payload if it reached its home
            stage (idempotent re-writes are harmless)."""
            write = (reg_idx >= 0) & (reg_idx % stages == stage)
            wslot = jnp.maximum(reg_idx // stages, 0)
            out_x = jnp.where(
                write,
                jax.lax.dynamic_update_index_in_dim(out_x, reg_x, wslot, 0),
                out_x,
            )
            if has_msa:
                out_m = jnp.where(
                    write,
                    jax.lax.dynamic_update_index_in_dim(
                        out_m, reg_m, wslot, 0
                    ),
                    out_m,
                )
            return out_x, out_m

        def rotate_reg(reg_x, reg_m, reg_idx):
            reg_x = jax.lax.ppermute(reg_x, axis_name, fwd_perm)
            if has_msa:
                reg_m = jax.lax.ppermute(reg_m, axis_name, fwd_perm)
            reg_idx = jax.lax.ppermute(reg_idx, axis_name, fwd_perm)
            return reg_x, reg_m, reg_idx

        def tick(carry, t):
            (x_act, m_act, out_x, out_m, xs, ms, reg_x, reg_m,
             reg_idx, xmk_act, mmk_act, xmk_s, mmk_s) = carry

            # --- feed: stage 0 consumes the drip register's current slot.
            # During cycle k = t//S, slot k has rotated (t mod S) hops, so
            # it now holds the stage-(t mod S) original = microbatch t.
            slot = jnp.minimum(t // stages, slots - 1)
            x_in = jnp.where(is_first, xs[slot], x_act)
            m_in = jnp.where(is_first, ms[slot], m_act) if has_msa else None
            # traveling masks feed exactly like their activations
            xmk_in = (jnp.where(is_first, xmk_s[slot], xmk_act)
                      if x_mask_mode == "travel" else None)
            mmk_in = (jnp.where(is_first, mmk_s[slot], mmk_act)
                      if msa_mask_mode == "travel" else None)

            x_act, m_act = apply_block(x_in, m_in, xmk_in, mmk_in)

            # --- the last stage's finished microbatch enters the return
            # ring (overwriting a payload that must already be harvested —
            # a full ring lap is longer than any harvest path), then every
            # stage harvests
            done = t - (stages - 1)
            fresh = jnp.where(is_last & (done >= 0) & (done < M), done, -1)
            reg_idx = jnp.where(is_last, fresh, reg_idx)
            reg_x = jnp.where(is_last, x_act, reg_x)
            if has_msa:
                reg_m = jnp.where(is_last, m_act, reg_m)
            out_x, out_m = harvest(out_x, out_m, reg_x, reg_m, reg_idx)

            # --- rotate all three rings.
            # activations: stage s -> s+1 (stage 0 ignores the wrapped
            # S-1 -> 0 handoff — it reads the feed register instead);
            # fused with the return ring, which shares the direction
            both = jax.lax.ppermute(
                jnp.stack([x_act, reg_x]), axis_name, fwd_perm
            )
            x_act, reg_x = both[0], both[1]
            if has_msa:
                both = jax.lax.ppermute(
                    jnp.stack([m_act, reg_m]), axis_name, fwd_perm
                )
                m_act, reg_m = both[0], both[1]
            reg_idx = jax.lax.ppermute(reg_idx, axis_name, fwd_perm)
            # traveling masks follow their activations forward: the mask
            # THIS stage just used (xmk_in) is what the next stage needs
            # for the same microbatch
            if x_mask_mode == "travel":
                xmk_act = jax.lax.ppermute(xmk_in, axis_name, fwd_perm)
            if msa_mask_mode == "travel":
                mmk_act = jax.lax.ppermute(mmk_in, axis_name, fwd_perm)
            # feed drip: the consumption-cycle slot moves one hop toward
            # stage 0 (data past stage 0 becomes garbage, never re-read)
            xs = xs.at[slot].set(
                jax.lax.ppermute(xs[slot], axis_name, back_perm)
            )
            if has_msa:
                ms = ms.at[slot].set(
                    jax.lax.ppermute(ms[slot], axis_name, back_perm)
                )
            if x_mask_mode == "travel":
                xmk_s = xmk_s.at[slot].set(
                    jax.lax.ppermute(xmk_s[slot], axis_name, back_perm)
                )
            if msa_mask_mode == "travel":
                mmk_s = mmk_s.at[slot].set(
                    jax.lax.ppermute(mmk_s[slot], axis_name, back_perm)
                )
            return (x_act, m_act, out_x, out_m, xs, ms, reg_x, reg_m,
                    reg_idx, xmk_act, mmk_act, xmk_s, mmk_s), None

        def drain(carry, _):
            """Return-ring rides can outlast the compute schedule by up to
            S-2 hops (microbatch M-2's home is S-1 hops from the last
            stage); rotate + harvest only, no compute."""
            out_x, out_m, reg_x, reg_m, reg_idx = carry
            out_x, out_m = harvest(out_x, out_m, reg_x, reg_m, reg_idx)
            reg_x, reg_m, reg_idx = rotate_reg(reg_x, reg_m, reg_idx)
            return (out_x, out_m, reg_x, reg_m, reg_idx), None

        carry0 = (x0, m0, out_x, out_m, xs, ms, x0, m0, reg_idx0,
                  xmk0, mmk0,
                  xmk if x_mask_mode == "travel" else None,
                  mmk if msa_mask_mode == "travel" else None)
        (x_act, m_act, out_x, out_m, xs, ms, reg_x, reg_m, reg_idx,
         *_mask_state), _ = (
            jax.lax.scan(tick, carry0, jnp.arange(ticks))
        )
        drain_ticks = max(0, stages - 2)
        if drain_ticks:
            (out_x, out_m, reg_x, reg_m, reg_idx), _ = jax.lax.scan(
                drain,
                (out_x, out_m, reg_x, reg_m, reg_idx),
                None,
                length=drain_ticks,
            )
        out_x = out_x[None]  # restore the sharded leading stage axis
        out_m = out_m[None] if has_msa else None
        return out_x, out_m

    out_x, out_m = run(stage_params, xs, ms, x_mask_v, msa_mask_v,
                       stage_flags)
    out_x = _un_round_robin(out_x, M).reshape((b,) + x.shape[1:])
    if has_msa:
        out_m = _un_round_robin(out_m, M).reshape((b,) + m.shape[1:])
    return out_x, out_m


def alphafold2_apply_pp(
    params,
    cfg: Alphafold2Config,
    seq,
    msa,
    mesh: Mesh,
    *,
    axis_name: str = "pipe",
    seq_axis: str = None,
    microbatches: int = None,
    mask=None,
    msa_mask=None,
    templates=None,
    templates_mask=None,
    embedds=None,
):
    """FULL-model forward with the trunk pipelined over `mesh[axis_name]`
    (optionally composed with sequence parallelism over `seq_axis`).

    Embeddings, the (optional) template tower, and the distogram head run
    replicated — a negligible share of the FLOPs; the trunk stages over
    the pipe axis via the models/alphafold2.py `trunk_fn` hook. The
    front's masks are PER-EXAMPLE, so this integration rides the
    traveling-mask rings. Deterministic path (pipeline contract); parity
    with the replicated `alphafold2_apply` is pinned full-model on the
    8-device mesh (tests/test_pipeline.py).
    """
    from alphafold2_tpu.models.alphafold2 import alphafold2_apply

    if cfg.reversible:
        raise ValueError(
            "the pipeline trunk uses the sequential layer list; set "
            "reversible=False (activation memory scales O(batch/S) via "
            "the schedule instead)"
        )
    if embedds is not None and seq_axis is not None:
        # same contract as alphafold2_apply_sp: the embedds-substitute
        # stream has no row axis to shard, so the SP layer body cannot
        # run on it — plain PP (seq_axis=None) handles embedds fine
        raise ValueError(
            "embedds is not supported with seq_axis (the substitute MSA "
            "stream has no row axis to shard); use seq_axis=None"
        )

    def trunk_fn(layers, cfg_, x, m, x_mask, m_mask, rng):
        del rng  # deterministic path (pipeline_trunk_apply contract)
        return pipeline_trunk_apply(
            layers, cfg_, x, m, mesh,
            axis_name=axis_name, microbatches=microbatches,
            x_mask=x_mask, msa_mask=m_mask, seq_axis=seq_axis,
        )

    return alphafold2_apply(
        params, cfg, seq, msa,
        mask=mask, msa_mask=msa_mask,
        templates=templates, templates_mask=templates_mask,
        embedds=embedds, trunk_fn=trunk_fn,
    )
