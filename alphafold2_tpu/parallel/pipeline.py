"""Pipeline parallelism: the trunk staged over a mesh axis.

The last absent row of SURVEY.md §2.2 ("optional: stage the depth-48 trunk
across pods"). GPipe-style schedule, TPU-native mechanics: the depth-stacked
layer parameters are SHARDED over the "pipe" mesh axis (each device owns
depth/S consecutive layers), microbatches stream through the stages, and
the only communication is a neighbor `ppermute` of activations per tick —
exactly the collective the hardware's ring likes. Everything runs inside
one `shard_map` + `lax.scan` over ticks; no host round-trips.

Schedule (S stages, M microbatches, T = M + S - 1 ticks):

  tick t: stage 0 ingests microbatch t (zeros once the real ones run out);
          every stage applies its layer block to its resident activation;
          activations ppermute stage s -> s+1; the last stage's result for
          microbatch t - (S-1) lands in the output buffer.

Bubble fraction is (S-1)/T — the standard GPipe cost; pick M >= 4*S to
amortize. Parity vs the replicated sequential trunk is tested on the
8-device CPU mesh (tests/test_pipeline.py).

The per-stage body is the REAL trunk layer (models/trunk.py
`trunk_layer_apply`, deterministic path): pair axial self-attn, MSA axial
self-attn (tied rows allowed — rows are NOT sharded here, so no psum is
needed), cross-attention (flat or aligned), feed-forwards.

What this scales — and what it does not (yet): the per-stage PARAMETER and
optimizer state is 1/S of the trunk (the reason to pipeline depth-48
across pods). The microbatch input stack and output buffer are currently
replicated across stages for schedule simplicity, so per-chip ACTIVATION
memory is bounded by the global batch, not batch/S — compose with smaller
per-pipeline batches or the SP trunk when activations dominate.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from alphafold2_tpu.models.config import Alphafold2Config
from alphafold2_tpu.models.reversible import stack_layers
from alphafold2_tpu.models.trunk import trunk_layer_apply


def pipeline_trunk_apply(
    layers,
    cfg: Alphafold2Config,
    x,
    m,
    mesh: Mesh,
    *,
    axis_name: str = "pipe",
    microbatches: int = None,
    x_mask=None,
    msa_mask=None,
):
    """Run the sequential trunk pipelined over `mesh[axis_name]`.

    Args (global layouts):
      layers: list of trunk_layer_init params (depth % stages == 0);
      x: (b, n, n, d) pair grid; m: (b, rows, cols, d) MSA or None;
      microbatches: how many microbatches to split b into (default =
        stage count; b % microbatches == 0).

    Deterministic path only. Masks must be batch-broadcast (shape (1, ...))
    or None: microbatch slicing of per-example masks would need them to
    travel with the activations (not implemented).

    Returns (x, m) in global layouts, numerically identical to
    sequential_trunk_apply with the same layers.
    """
    stages = mesh.shape[axis_name]
    depth = len(layers)
    if depth % stages != 0:
        raise ValueError(f"depth {depth} must divide into {stages} stages")
    if any(cfg.layer_sparse):
        raise ValueError(
            "sparse layers are not supported in the pipeline trunk (the "
            "scanned stage body is uniform); use the sequential trunk"
        )
    for mask in (x_mask, msa_mask):
        if mask is not None and mask.shape[0] != 1:
            raise ValueError("pipeline masks must be batch-broadcast (b=1)")

    b = x.shape[0]
    M = microbatches or stages
    if b % M != 0:
        raise ValueError(f"batch {b} must divide into {M} microbatches")
    mb = b // M

    # materialize broadcast masks at microbatch size so the layer body's
    # fold-into-batch reshapes line up
    if x_mask is not None:
        x_mask = jnp.tile(x_mask, (mb,) + (1,) * (x_mask.ndim - 1))
    if msa_mask is not None:
        msa_mask = jnp.tile(msa_mask, (mb,) + (1,) * (msa_mask.ndim - 1))

    has_msa = m is not None
    stacked = stack_layers(list(layers))  # (depth, ...) leaves
    per_stage = depth // stages
    ticks = M + stages - 1

    # microbatch-leading stacks: (M, mb, ...)
    xs = x.reshape((M, mb) + x.shape[1:])
    ms = m.reshape((M, mb) + m.shape[1:]) if has_msa else None

    def reshape_stage(t):
        # (depth, ...) -> (stages, per_stage, ...): shard leading axis
        return t.reshape((stages, per_stage) + t.shape[1:])

    stage_params = jax.tree_util.tree_map(reshape_stage, stacked)

    in_specs = (
        jax.tree_util.tree_map(lambda _: P(axis_name), stage_params),
        P(None),  # xs: every stage sees the full microbatch stack (stage 0 reads it)
        P(None) if has_msa else None,
    )
    out_specs = (P(None), P(None) if has_msa else None)

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=False,
    )
    def run(sp, xs, ms):
        # sp leaves: (1, per_stage, ...) — this device's layer block
        my_layers = jax.tree_util.tree_map(lambda t: t[0], sp)
        stage = jax.lax.axis_index(axis_name)
        is_first = stage == 0
        is_last = stage == stages - 1
        fwd_perm = [(s, s + 1) for s in range(stages - 1)]

        def apply_block(x_act, m_act):
            def body(carry, lp):
                cx, cm = carry
                cx, cm = trunk_layer_apply(
                    lp, cfg, cx, cm, x_mask=x_mask, msa_mask=msa_mask
                )
                return (cx, cm), None

            (x_act, m_act), _ = jax.lax.scan(
                body, (x_act, m_act), my_layers
            )
            return x_act, m_act

        x0 = jnp.zeros((mb,) + xs.shape[2:], xs.dtype)
        m0 = jnp.zeros((mb,) + ms.shape[2:], ms.dtype) if has_msa else None
        out_x = jnp.zeros_like(xs)
        out_m = jnp.zeros_like(ms) if has_msa else None

        def tick(carry, t):
            x_act, m_act, out_x, out_m = carry
            # stage 0 ingests microbatch t (or zeros past the end)
            feed_idx = jnp.minimum(t, M - 1)
            x_in = jnp.where(is_first, xs[feed_idx], x_act)
            m_in = jnp.where(is_first, ms[feed_idx], m_act) if has_msa else None

            x_act, m_act = apply_block(x_in, m_in)

            # the last stage finished microbatch t-(S-1) this tick
            done = t - (stages - 1)
            write = is_last & (done >= 0)
            widx = jnp.maximum(done, 0)
            out_x = jnp.where(
                write,
                jax.lax.dynamic_update_index_in_dim(out_x, x_act, widx, 0),
                out_x,
            )
            if has_msa:
                out_m = jnp.where(
                    write,
                    jax.lax.dynamic_update_index_in_dim(out_m, m_act, widx, 0),
                    out_m,
                )

            # hand activations to the next stage (last stage's output is
            # dropped by the permute — nothing maps to stage 0's input)
            x_act = jax.lax.ppermute(x_act, axis_name, fwd_perm)
            if has_msa:
                m_act = jax.lax.ppermute(m_act, axis_name, fwd_perm)
            return (x_act, m_act, out_x, out_m), None

        (x_act, m_act, out_x, out_m), _ = jax.lax.scan(
            tick, (x0, m0, out_x, out_m), jnp.arange(ticks)
        )
        # only the last stage holds real outputs; psum with zero
        # contributions elsewhere replicates them to every shard (a
        # one-to-all ppermute is not a permutation)
        out_x = jax.lax.psum(jnp.where(is_last, out_x, 0), axis_name)
        if has_msa:
            out_m = jax.lax.psum(jnp.where(is_last, out_m, 0), axis_name)
        return out_x, out_m

    out_x, out_m = run(stage_params, xs, ms)
    out_x = out_x.reshape((b,) + x.shape[1:])
    if has_msa:
        out_m = out_m.reshape((b,) + m.shape[1:])
    return out_x, out_m
