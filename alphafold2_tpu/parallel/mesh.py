"""Device mesh construction.

The reference's distribution story is two empty launcher files intended for
DeepSpeed/Lightning over NCCL (reference training_scripts/deepspeed.py,
lightning.py — both 0 bytes). The TPU-native replacement is a
`jax.sharding.Mesh` over which shardings are annotated and XLA inserts the
collectives (psum over ICI for gradients, all-gathers for TP) — there is no
hand-written transport layer to build.

Mesh axes used across the framework:
  * "data"  — batch data parallelism (gradient psum rides ICI);
  * "model" — tensor parallelism over attention heads / FF inner dim.
"""

from __future__ import annotations

from typing import Mapping, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh


def make_mesh(
    axes: Mapping[str, int],
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a mesh with the given {axis_name: size} layout.

    Axis order follows dict order; sizes must multiply to the device count
    used. `devices` defaults to all visible devices (trimmed to the product
    of the axis sizes).
    """
    names = tuple(axes.keys())
    sizes = tuple(axes.values())
    n = int(np.prod(sizes))
    devs = list(devices) if devices is not None else jax.devices()
    if len(devs) < n:
        raise ValueError(f"need {n} devices for mesh {dict(axes)}, have {len(devs)}")
    grid = np.asarray(devs[:n]).reshape(sizes)
    return Mesh(grid, names)


def data_parallel_mesh(n: Optional[int] = None) -> Mesh:
    """All (or the first n) devices on a single "data" axis."""
    devs = jax.devices()
    n = n if n is not None else len(devs)
    return make_mesh({"data": n}, devs)
