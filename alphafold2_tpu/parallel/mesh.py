"""Device mesh construction.

The reference's distribution story is two empty launcher files intended for
DeepSpeed/Lightning over NCCL (reference training_scripts/deepspeed.py,
lightning.py — both 0 bytes). The TPU-native replacement is a
`jax.sharding.Mesh` over which shardings are annotated and XLA inserts the
collectives (psum over ICI for gradients, all-gathers for TP) — there is no
hand-written transport layer to build.

Mesh axes used across the framework:
  * "data"  — batch data parallelism (gradient psum rides ICI);
  * "model" — tensor parallelism over attention heads / FF inner dim;
  * "seq"   — sequence/context parallelism (ring / Ulysses attention);
  * "sp"    — the sequence-parallel trunk's row axis (tests' short name);
  * "pipe"  — pipeline parallelism over trunk layers.
"""

from __future__ import annotations

from typing import Mapping, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh

from alphafold2_tpu import compat

# Canonical mesh-axis names. The static analyzer's sharding pass
# (alphafold2_tpu/analysis/sharding_lint.py) checks every string-literal
# axis appearing in a PartitionSpec under parallel/ against this registry —
# a typo'd axis name ("dat", "sq") otherwise survives until a mesh lookup
# KeyErrors mid-trace on real chips. Add the name HERE when introducing a
# new mesh axis.
KNOWN_AXES = frozenset({"data", "model", "seq", "sp", "pipe"})


def _default_devices(axes: Mapping[str, int], n: int) -> list:
    """Default device list for a mesh of extent `n`: ALL processes'
    devices (`jax.devices()` — the GLOBAL view), with the multi-process
    footgun closed explicitly. Single-process, a product smaller than the
    device count trims to a prefix (the long-standing test idiom:
    {"seq": 2} on the 8-device virtual platform). Multi-process, a
    trimmed prefix would be the first host(s)' devices only — a mesh
    that LOOKS like it spans the pod but quietly dropped every other
    process — so there the product must equal `jax.device_count()`
    exactly; deliberate subsets pass `devices=` explicitly
    (e.g. `jax.local_devices()` for a host-local mesh)."""
    devs = list(jax.devices())
    if jax.process_count() > 1 and n != jax.device_count():
        raise ValueError(
            f"mesh {dict(axes)} covers {n} devices but this is a "
            f"{jax.process_count()}-process run with "
            f"jax.device_count()={jax.device_count()} global "
            f"({jax.local_device_count()} local) devices — size the axes "
            "to the GLOBAL device count, or pass an explicit `devices=` "
            "subset (jax.local_devices() for a deliberately host-local "
            "mesh)"
        )
    return devs


def make_mesh(
    axes: Mapping[str, int],
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a mesh with the given {axis_name: size} layout.

    Axis order follows dict order; sizes must multiply to the device
    count used. `devices` defaults to all visible devices across ALL
    processes (`jax.devices()`, trimmed to the product of the axis
    sizes); in a multi-process run the default requires the product to
    equal `jax.device_count()` exactly — see `_default_devices`.
    """
    names = tuple(axes.keys())
    sizes = tuple(axes.values())
    n = int(np.prod(sizes))
    devs = list(devices) if devices is not None else _default_devices(axes, n)
    if len(devs) < n:
        raise ValueError(f"need {n} devices for mesh {dict(axes)}, have {len(devs)}")
    grid = np.asarray(devs[:n]).reshape(sizes)
    return Mesh(grid, names)


def data_parallel_mesh(n: Optional[int] = None, *, local: bool = False) -> Mesh:
    """All (or the first n) devices on a single "data" axis.

    The default derives n from `jax.device_count()` — the GLOBAL count,
    spanning every process of a pod. `local=True` derives from
    `jax.local_device_count()` over `jax.local_devices()` instead, for
    callers that WANT a host-local mesh (per-host preprocessing,
    single-host tools) — the choice is explicit either way, so a
    single-process assumption can never silently produce a local-only
    mesh on a pod."""
    if local:
        devs = jax.local_devices()
        n = n if n is not None else jax.local_device_count()
        return make_mesh({"data": n}, devs)
    if n is None:
        n = jax.device_count()
    # default devices: the multi-process exact-cover guard applies — an
    # explicit n that covers only some hosts' devices must error, not
    # silently build a prefix (one-host) mesh
    return make_mesh({"data": n})


def hybrid_mesh(
    dcn_axes: Mapping[str, int],
    ici_axes: Mapping[str, int],
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Mesh spanning multiple TPU slices: outer axes cross slices (DCN),
    inner axes stay within one slice (ICI).

    Collective placement follows bandwidth: put the gradient psum of data
    parallelism on a `dcn_axes` axis (one bandwidth-light all-reduce per
    step) and the bandwidth-hungry strategies — tensor parallel's
    all-gathers, sequence parallel's ring/all-to-all — on `ici_axes`, so
    they ride the intra-slice interconnect. This is the multi-slice
    extension of SURVEY.md §2.2's communication-backend row (the
    reference's intended NCCL transport, empty
    training_scripts/deepspeed.py, has no slice topology notion at all).

    On real multi-slice TPU (devices expose `slice_index`) the assignment
    uses jax's hybrid mesh builder, which maps inner axes onto each
    slice's ICI torus. Elsewhere (CPU meshes, single slice) it falls back
    to contiguous grouping — jax orders devices by process, so inner axes
    still land within a host when sizes align.

    Example: 4 slices x 8 chips, DP over slices, SP within:
        hybrid_mesh({"data": 4}, {"seq": 8})
    """
    dcn_names, ici_names = tuple(dcn_axes), tuple(ici_axes)
    dcn_sizes, ici_sizes = tuple(dcn_axes.values()), tuple(ici_axes.values())
    names = dcn_names + ici_names
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate axis name across dcn/ici axes: {names}")
    n_dcn = int(np.prod(dcn_sizes))
    n_ici = int(np.prod(ici_sizes))
    n = n_dcn * n_ici
    devs = (
        list(devices) if devices is not None
        else _default_devices({**dcn_axes, **ici_axes}, n)
    )
    if len(devs) < n:
        raise ValueError(
            f"need {n} devices for mesh {dict(dcn_axes)} x {dict(ici_axes)}, "
            f"have {len(devs)}"
        )

    by_slice: dict = {}
    for d in devs:
        by_slice.setdefault(getattr(d, "slice_index", None), []).append(d)

    if None in by_slice or len(by_slice) == 1:
        # no slice topology (CPU meshes, single slice): contiguous grouping —
        # jax orders devices by process, so ICI axes land within a host
        # when sizes align
        return make_mesh({**dcn_axes, **ici_axes}, devs[:n])

    # real multi-slice topology: select devices slice-aware — whole slices
    # for the DCN extent, an equal n_ici-chip granule from each — so the
    # hybrid builder always sees equal granules (a naive devs[:n] prefix can
    # split a slice unevenly), and NEVER fall back silently: a contiguous
    # reshape here would straddle ICI axes across slices, putting per-layer
    # all-gathers on DCN — the exact pathology this function exists to avoid
    if len(by_slice) < n_dcn:
        raise ValueError(
            f"{dict(dcn_axes)} needs {n_dcn} slices, devices span "
            f"{len(by_slice)}"
        )
    groups = [by_slice[s] for s in sorted(by_slice)[:n_dcn]]
    sizes = sorted({len(g) for g in groups})
    if sizes != [n_ici]:
        # jax's per-granule mesh builder maps a granule onto the slice's
        # physical torus; an arbitrary chip subset of a slice generally
        # does not form one, so partial slices fail deep inside jax with
        # an opaque error. Require whole slices and say so up front; a
        # deliberate subset can be passed via `devices=`.
        raise ValueError(
            f"{dict(ici_axes)} needs whole slices of exactly {n_ici} chips; "
            f"selected slices have {sizes} — size the ICI axes to the slice "
            f"chip count, or pass an explicit `devices=` subset"
        )
    selected = [d for g in groups for d in g]

    # same-rank contract: per-slice shape padded with 1s on the DCN dims,
    # across-slice shape padded with 1s on the ICI dims
    grid = compat.create_hybrid_device_mesh(
        mesh_shape=(1,) * len(dcn_sizes) + ici_sizes,
        dcn_mesh_shape=dcn_sizes + (1,) * len(ici_sizes),
        devices=selected,
    )
    return Mesh(grid, names)
