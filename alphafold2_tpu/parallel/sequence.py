"""Sequence / context parallelism: ring attention, Ulysses all_to_all
attention, and sequence-parallel axial transposes.

The reference has no comm-based sequence parallelism (SURVEY.md §2.2: its
long-context story is architectural — axial factorization, block-sparse
attention, KV compression). A TPU-native framework at multi-chip scale needs
the communication-based complement, and these are its three primitives, all
designed to run inside `shard_map` over a mesh axis so XLA lowers the
communication onto ICI:

  * `ring_attention` — exact blockwise attention for sequences longer than
    one chip's HBM: K/V shards rotate around the ring via `ppermute` while
    each chip streams flash-style log-sum-exp softmax accumulation over its
    resident Q shard. Communication overlaps compute block by block;
    memory per chip is O(n/P) in sequence.
  * `ulysses_attention` — all_to_all (DeepSpeed-Ulysses-style) sequence
    parallelism: resharding flips (sequence-sharded, all heads) into
    (head-sharded, full sequence) so each chip runs a plain dense attention
    over its head group, then flips back. Two all_to_alls per attention;
    best when heads >= chips and the sequence fits per-chip after the flip.
  * `axial_alltoall_transpose` — for the axial (row/column) attention
    pattern: swaps which grid axis is sharded between the row pass and the
    column pass. Each axial pass is embarrassingly parallel over its
    folded-into-batch axis (reference alphafold2.py:276-283 semantics); the
    transpose is the only communication.

All softmax statistics accumulate in float32 with -inf masking handled the
same way as the Pallas block-sparse kernel (ops/sparse_kernel.py): masked
logits never contribute, fully-masked queries return zeros.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from alphafold2_tpu import compat
from alphafold2_tpu.ops import dispatch as _dispatch
from alphafold2_tpu.ops.flash import (
    flash_attention as _flash_attention,
    hop_attention_lse as _hop_attention_lse,
    merge_lse as _merge_lse,
    stream_block as _stream_block,
)
from alphafold2_tpu.parallel.overlap import overlap_enabled

_NEG_INF = float("-inf")


def _hop(k_blk, v_blk, bias_blk, axis_name, perm):
    """One ring hop: the neighbor copy of the K/V shard and its bias."""
    return (
        jax.lax.ppermute(k_blk, axis_name, perm),
        jax.lax.ppermute(v_blk, axis_name, perm),
        jax.lax.ppermute(bias_blk, axis_name, perm),
    )


def ring_attention(q, k, v, axis_name: str, mask=None, use_kernel="auto",
                   overlap=None):
    """Exact ring attention over a sharded sequence axis.

    Call inside `shard_map` with the sequence axis sharded over `axis_name`.

    Args:
      q, k, v: (b, n_local, h, d) — this chip's sequence shard.
      mask: (b, n_local) bool key-validity for the local shard (key-side
        masking, matching the reference's key_padding semantics,
        alphafold2.py:156-161 / DeepSpeed attn_mask_mode='add').
      use_kernel: per-hop compute path. "auto" uses the Pallas flash
        kernel on TPU for supported shapes whose PER-HOP key length
        nk_local >= ops/flash.py auto_min_j() (each hop emits (out, lse)
        and hops combine in log space —
        ops/flash_kernel.flash_attention_lse); below that threshold the
        hop runs the XLA stream_block recurrence — the crossover was
        measured on single-device e2e shapes (PERF.md session 4), not on
        ring hops, so force with True (interpret mode off-TPU, for tests)
        or AF2_FLASH_AUTO_MIN_J=0 to get the kernel on short shards.
      overlap: schedule selection. True = double-buffered (issue hop
        i+1's ppermute BEFORE computing hop i's block, so the ICI
        transfer hides under the current block's compute); False = the
        synchronous rotate-then-compute schedule; None (default) reads
        `AF2_COMM_OVERLAP` (parallel/overlap.py, default on). Both
        schedules visit the blocks in the same order with the same
        arithmetic — exact parity (tests/test_overlap.py), verified
        structurally by analysis/overlap_lint.py.

    Returns: (b, n_local, h, d) attention output for the local Q shard.
    """
    b, n_local, h, d = q.shape
    nk_local = k.shape[1]  # may differ from n_local for cross-attention
    scale = d ** -0.5
    num_shards = jax.lax.psum(1, axis_name)
    overlap = overlap_enabled(overlap)

    # mark constant-built carries as device-varying over the ring axis so
    # the fori_loop carry types match after the first ppermute
    def varying(x):
        return compat.pcast(x, (axis_name,), to="varying")

    bias = (
        varying(jnp.zeros((b, nk_local), jnp.float32))
        if mask is None
        else jnp.where(mask, 0.0, _NEG_INF).astype(jnp.float32)
    )
    perm = [(i, (i + 1) % num_shards) for i in range(num_shards)]

    # the SHARED resolution point (ops/dispatch.py, op "merge_lse" — the
    # ring hop's registered name): honors AF2_DISABLE_FLASH_KERNEL and
    # the AF2_KERNEL_BACKEND[_MERGE_LSE] overrides, and raises loudly
    # when forcing an unsupported shape
    if _dispatch.resolve(
        "merge_lse", request=use_kernel, i=n_local, j=nk_local, dh=d
    ) == _dispatch.ARM_PALLAS_TPU:
        return _ring_attention_kernel(
            q, k, v, bias, axis_name, scale, num_shards, perm, overlap
        )

    m0 = varying(jnp.full((b, h, n_local), _NEG_INF, jnp.float32))
    l0 = varying(jnp.zeros((b, h, n_local), jnp.float32))
    acc0 = varying(jnp.zeros((b, h, n_local, d), jnp.float32))

    if overlap and num_shards > 1:
        # DOUBLE-BUFFERED schedule: hop 1's ppermute is issued before the
        # resident block's compute, and each loop body issues hop i+1's
        # ppermute before computing hop i's (already-arrived) block — the
        # neighbor copy rides the ICI while the MXU runs the current
        # block, instead of fencing it. Still exactly P-1 copies: the
        # loop runs hops 1..P-2 and the last arrival computes outside.
        k_nxt, v_nxt, b_nxt = _hop(k, v, bias, axis_name, perm)
        m, l, acc = _stream_block(q, k, v, bias, m0, l0, acc0, scale)

        def body(_, carry):
            m, l, acc, k_blk, v_blk, bias_blk = carry
            k_n, v_n, b_n = _hop(k_blk, v_blk, bias_blk, axis_name, perm)
            m, l, acc = _stream_block(
                q, k_blk, v_blk, bias_blk, m, l, acc, scale
            )
            return m, l, acc, k_n, v_n, b_n

        m, l, acc, k_last, v_last, b_last = jax.lax.fori_loop(
            1, num_shards - 1, body, (m, l, acc, k_nxt, v_nxt, b_nxt)
        )
        m, l, acc = _stream_block(q, k_last, v_last, b_last, m, l, acc, scale)
    else:
        # SYNCHRONOUS schedule: resident block first, then
        # rotate-before-compute for the remaining num_shards-1 blocks —
        # exactly P-1 neighbor copies, each fencing its block's compute.
        # Kept as the overlap-off reference arm (A/B legs, overlap-lint
        # fixtures) and the num_shards == 1 degenerate case.
        m, l, acc = _stream_block(q, k, v, bias, m0, l0, acc0, scale)

        def body(_, carry):
            m, l, acc, k_blk, v_blk, bias_blk = carry
            k_blk, v_blk, bias_blk = _hop(
                k_blk, v_blk, bias_blk, axis_name, perm
            )
            m, l, acc = _stream_block(
                q, k_blk, v_blk, bias_blk, m, l, acc, scale
            )
            return m, l, acc, k_blk, v_blk, bias_blk

        m, l, acc, _, _, _ = jax.lax.fori_loop(
            1, num_shards, body, (m, l, acc, k, v, bias)
        )
    out = acc / jnp.where(l > 0, l, 1.0)[..., None]  # zeros for fully-masked q
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def _ring_attention_kernel(q, k, v, bias, axis_name, scale, num_shards, perm,
                           overlap=False):
    """Ring hops through the Pallas flash kernel: each hop produces its
    local (out, lse) fused in VMEM (ops/flash_kernel.flash_attention_lse),
    and hops merge in log space (ops/flash.py merge_lse — the shared hop
    interface). The communication pattern is identical to the XLA path
    (P-1 neighbor ppermutes, double-buffered when `overlap`), only the
    per-hop compute is fused. The kernel entry is ops/flash.py
    `hop_attention_lse` (zero-mass lse sign flip included) — this module
    never imports a kernel module directly (the dispatch lint's import
    monopoly)."""
    b, n_local, h, d = q.shape

    def fold(t):
        return t.transpose(0, 2, 1, 3).reshape(b * h, t.shape[1], d)

    qf = fold(q)

    def hop_compute(kf, vf, bias_blk):
        return _hop_attention_lse(
            qf, kf, vf, jnp.repeat(bias_blk, h, axis=0), scale
        )

    kf0, vf0 = fold(k), fold(v)

    if overlap and num_shards > 1:
        # double-buffered: hop i+1's ppermute issues before hop i's
        # kernel launch (see the XLA-path schedule above)
        k_nxt, v_nxt, b_nxt = _hop(kf0, vf0, bias, axis_name, perm)
        out, lse = hop_compute(kf0, vf0, bias)

        def body(_, carry):
            out, lse, k_blk, v_blk, bias_blk = carry
            k_n, v_n, b_n = _hop(k_blk, v_blk, bias_blk, axis_name, perm)
            out_h, lse_h = hop_compute(k_blk, v_blk, bias_blk)
            out, lse = _merge_lse(out, lse, out_h, lse_h)
            return out, lse, k_n, v_n, b_n

        out, lse, k_last, v_last, b_last = jax.lax.fori_loop(
            1, num_shards - 1, body, (out, lse, k_nxt, v_nxt, b_nxt)
        )
        out_h, lse_h = hop_compute(k_last, v_last, b_last)
        out, _ = _merge_lse(out, lse, out_h, lse_h)
    else:
        out, lse = hop_compute(kf0, vf0, bias)

        def body(_, carry):
            out, lse, k_blk, v_blk, bias_blk = carry
            k_blk, v_blk, bias_blk = _hop(
                k_blk, v_blk, bias_blk, axis_name, perm
            )
            out_h, lse_h = hop_compute(k_blk, v_blk, bias_blk)
            out, lse = _merge_lse(out, lse, out_h, lse_h)
            return out, lse, k_blk, v_blk, bias_blk

        out, lse, _, _, _ = jax.lax.fori_loop(
            1, num_shards, body, (out, lse, kf0, vf0, bias)
        )
    return out.reshape(b, h, n_local, d).transpose(0, 2, 1, 3).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str, mask=None):
    """All_to_all (Ulysses-style) sequence-parallel attention.

    Call inside `shard_map`; sequence axis sharded over `axis_name`, heads
    divisible by the axis size. Reshards to (full sequence, heads/P) per
    chip, runs dense flash-style attention locally, reshards back.

    Args/returns as `ring_attention`.
    """
    b, n_local, h, d = q.shape
    num_shards = jax.lax.psum(1, axis_name)
    if h % num_shards != 0:
        raise ValueError(f"heads ({h}) must divide by the sp axis ({num_shards})")

    # (b, n_local, h, d) -> (b, n, h_local, d): split heads, concat sequence
    def flip(t):
        return jax.lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1, tiled=True)

    qg, kg, vg = flip(q), flip(k), flip(v)
    if mask is None:
        bias = jnp.zeros((b, n_local * num_shards), jnp.float32)
    else:
        gathered = jax.lax.all_gather(mask, axis_name, tiled=True)  # (b*P, n_local)?
        # all_gather(tiled) concatenates over axis 0; reshape back to (b, n)
        bias = jnp.where(
            gathered.reshape(num_shards, b, n_local).transpose(1, 0, 2).reshape(b, -1),
            0.0,
            _NEG_INF,
        ).astype(jnp.float32)

    # fused/blockwise attention over the gathered sequence via the standard
    # dispatch (ops/flash.py): Pallas kernel on TPU, XLA K/V streaming
    # elsewhere — the full (n, n) logit tensor never materializes either
    # way, which is the point of sequence parallelism at long n
    out = _flash_attention(qg, kg, vg, bias, scale=d ** -0.5, kv_block=2048)

    # (b, n, h_local, d) -> (b, n_local, h, d)
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)


def sequence_parallel_axial_attention(params, cfg, x, axis_name: str, mask=None, rng=None):
    """The trunk's axial attention, sequence-parallel over the grid's row
    axis (SURVEY.md §2.2: 'shard the folded-into-batch axis').

    Call inside `shard_map` with x (b, rows_local, cols, d) row-sharded over
    `axis_name` (and mask (b, rows_local, cols)). Semantics match
    ops.attention.axial_attention_apply for self-attention: the row pass is
    embarrassingly parallel (rows are the folded batch), the column pass
    runs after an `all_to_all` grid transpose, and the two results sum in
    the row-sharded layout. One all_to_all pair per call — the only
    communication.

    Tied-row attention needs a cross-shard logit psum and is not supported
    here; keep tied-row layers on the replicated path.

    Dropout: `rng` is folded with the shard index so masks are independent
    across shards (the exact single-device mask pattern is not reproduced —
    documented divergence; rng=None is bit-identical).
    """
    from alphafold2_tpu.ops.attention import attention_apply

    b, h_local, w, d = x.shape

    if rng is not None:
        rng = jax.random.fold_in(rng, jax.lax.axis_index(axis_name))
        rng_col, rng_row = jax.random.split(rng)
    else:
        rng_col, rng_row = None, None

    # row pass: fold (sharded) rows into batch, attend along the full width
    row_x = x.reshape(b * h_local, w, d)
    row_mask = mask.reshape(b * h_local, w) if mask is not None else None
    row_out = attention_apply(
        params["attn_height"], cfg, row_x, mask=row_mask, rng=rng_row
    ).reshape(b, h_local, w, d)

    # column pass: transpose shard axis rows->cols, fold cols into batch
    xc = axial_alltoall_transpose(x, axis_name, row_sharded=True)  # (b, H, w/P, d)
    h_full, w_local = xc.shape[1], xc.shape[2]
    if mask is not None:
        mc = axial_alltoall_transpose(mask[..., None], axis_name, row_sharded=True)[..., 0]
        col_mask = jnp.swapaxes(mc, 1, 2).reshape(b * w_local, h_full)
    else:
        col_mask = None
    col_x = jnp.swapaxes(xc, 1, 2).reshape(b * w_local, h_full, d)
    col_out = attention_apply(
        params["attn_width"], cfg, col_x, mask=col_mask, rng=rng_col
    )
    col_out = jnp.swapaxes(col_out.reshape(b, w_local, h_full, d), 1, 2)
    col_out = axial_alltoall_transpose(col_out, axis_name, row_sharded=False)

    return row_out + col_out


def tied_row_attention_sharded(params, cfg, x, axis_name: str, mask=None, rng=None):
    """MSA tied-row attention with the ROW axis sharded over the mesh.

    Tied-row attention shares one logit matrix across all MSA rows
    (reference alphafold2.py:142-150; ops/attention.py tie_dim). When rows
    are sharded, each chip holds a partial logit sum over its resident
    rows; one `psum` over `axis_name` completes the contraction
    (SURVEY.md §2.2: 'if rows are sharded, logits need a psum over the
    row-shard axis'). Everything else — softmax, per-row value mixing,
    output projection — stays local.

    Call inside `shard_map`: x (b, r_local, n, dim) with the row axis
    sharded; mask (b, r_local, n). Exactly matches
    `attention_apply(..., tie_dim=r_total)` on the gathered rows (dropout
    included: the shared logits mean every shard must draw the same mask
    from the same key — do NOT fold in the shard index).

    Returns (b, r_local, n, dim).
    """
    from alphafold2_tpu.ops.core import dropout as _dropout, linear as _linear

    dtype = cfg.dtype
    b, r_local, n, _ = x.shape
    h, dh = cfg.heads, cfg.dim_head
    num_shards = jax.lax.psum(1, axis_name)
    r_total = r_local * num_shards

    q = _linear(params["to_q"], x, dtype=dtype)
    kv = _linear(params["to_kv"], x, dtype=dtype)
    k, v = jnp.split(kv, 2, axis=-1)
    q, k, v = (t.reshape(b, r_local, n, h, dh) for t in (q, k, v))

    # partial logit sum over resident rows, completed by ONE psum over ICI
    scale = dh ** -0.5 * r_total ** -0.5
    logits = jnp.einsum("brihd,brjhd->bhij", q, k).astype(jnp.float32) * scale
    logits = jax.lax.psum(logits, axis_name)

    if mask is not None:
        # a position is valid only if valid in EVERY row, across all shards
        # (ops/attention.py tie_dim mask collapse, generalized)
        local_all = jnp.all(mask, axis=1)  # (b, n)
        global_all = jax.lax.psum(local_all.astype(jnp.int32), axis_name) == num_shards
        pair = global_all[:, None, :, None] & global_all[:, None, None, :]
        logits = jnp.where(pair, logits, jnp.finfo(jnp.float32).min)

    attn = jax.nn.softmax(logits, axis=-1).astype(dtype)
    attn = _dropout(rng, attn, cfg.dropout)

    out = jnp.einsum("bhij,brjhd->brihd", attn, v).reshape(b, r_local, n, h * dh)
    if cfg.gate:
        # per-row output gate from the resident rows' own queries — the
        # sharded twin of attention_apply's epilogue (ops/flash.py
        # apply_output_gate), elementwise so no extra collective.
        # Direct attribute access on purpose: cfg is an AttentionConfig
        # (the caller passes self_attn_config()), and a wrong config
        # type must raise rather than silently skip the gate while
        # params["to_gate"] trains nowhere
        from alphafold2_tpu.ops.flash import apply_output_gate

        out = apply_output_gate(
            out, _linear(params["to_gate"], x, dtype=dtype)
        )
    return _linear(params["to_out"], out, dtype=dtype)


def axial_alltoall_transpose(x, axis_name: str, row_sharded: bool = True):
    """Swap the sharded grid axis of a pair-representation shard.

    x: (b, rows_local, cols, d) when `row_sharded` (-> (b, rows, cols_local, d)),
    or the mirror when not. One all_to_all on ICI; this is the only
    communication between the row pass and the column pass of sequence-
    parallel axial attention (SURVEY.md §2.2 'Ulysses-style transpose').
    """
    if row_sharded:
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)
    return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)
