"""Sharding: NamedShardings for train states and batches over a mesh.

The tensor-parallel layout lives in the partition-rule REGISTRY
(`parallel/rules.py` — regex over named param paths -> PartitionSpec,
first match wins). This module binds matched specs to a concrete mesh.
The registry applies unchanged to the optimizer state (optax's mu/nu
subtrees mirror the param tree, so suffix rules match) and to the
reversible trunk's depth-stacked params (rank adaptation in
`rules.spec_for_leaf`); unmatched non-scalar leaves raise loudly.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from alphafold2_tpu.parallel.rules import (
    match_partition_rules,
    partition_rules,
    spec_for_leaf,
    tree_path_string,
)


def param_spec(path, leaf, *, tp: bool) -> P:
    """PartitionSpec for one param (or optimizer-state) leaf, by tree
    path. Back-compat shim over the registry: prefer
    `rules.match_partition_rules` for whole trees."""
    spec = spec_for_leaf(tree_path_string(path), leaf, partition_rules(tp))
    return spec if spec is not None else P()


def state_shardings(mesh: Mesh, state: Any, *, tp: bool = True):
    """NamedShardings for a full train state (params + opt state + step):
    the partition-rule registry matched over the named tree, bound to
    `mesh`. TP rules apply only when the mesh actually has a "model"
    axis; otherwise everything replicates."""
    has_model = tp and "model" in mesh.axis_names
    specs = match_partition_rules(partition_rules(has_model), state)
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def batch_shardings(mesh: Mesh, batch: Any, *, microbatched: bool = True):
    """Shard the per-device batch axis over "data". With `microbatched`,
    leaves are (accum, b, ...) and axis 1 is the batch axis."""
    axis = 1 if microbatched else 0

    def spec(leaf):
        parts = [None] * leaf.ndim
        if "data" in mesh.axis_names and leaf.ndim > axis:
            parts[axis] = "data"
        return NamedSharding(mesh, P(*parts))

    return jax.tree_util.tree_map(spec, batch)


def replicated(mesh: Mesh):
    return NamedSharding(mesh, P())


def host_to_global(tree: Any, shardings: Any):
    """Global jax.Arrays for a host-side pytree every process holds
    identically (same-seed init, restored checkpoint bytes): each leaf
    materializes onto `shardings` with each process feeding its OWN
    addressable shards — no cross-process transfer
    (compat.make_global_array_from_host). The standard way to pin a
    freshly-initialized or restored train state to a process-spanning
    mesh."""
    from alphafold2_tpu import compat

    return jax.tree_util.tree_map(
        compat.make_global_array_from_host, tree, shardings
    )
