"""Sharding rules: PartitionSpecs for params, optimizer state, and batches.

Tensor-parallel layout (the Megatron split, expressed as GSPMD annotations
rather than collective calls):
  * attention to_q / to_kv weights shard their OUTPUT (head) dim;
  * attention to_out weight shards its INPUT dim (XLA inserts the psum);
  * feed-forward proj_in shards output, proj_out shards input;
  * the KV-compression conv shards its output channels (per-head groups);
  * embeddings, norms, biases of row-sharded layers: replicated.

Rules match on parameter-tree path suffixes, so they apply unchanged to the
optimizer state (whose mu/nu subtrees mirror the param tree) and to the
reversible trunk's depth-stacked params (leading depth axis is detected by
leaf rank).
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _path_names(path) -> tuple:
    names = []
    for e in path:
        if isinstance(e, jax.tree_util.DictKey):
            names.append(str(e.key))
        elif isinstance(e, jax.tree_util.SequenceKey):
            names.append(str(e.idx))
        elif isinstance(e, jax.tree_util.GetAttrKey):
            names.append(str(e.name))
    return tuple(names)


def _tp_spec(names: tuple, leaf) -> P:
    """Tensor-parallel PartitionSpec for one param leaf (base rank, no
    depth-stacking)."""
    if not names:
        return P()
    leaf_name = names[-1]
    parent = names[-2] if len(names) >= 2 else ""
    if leaf_name == "w":
        if parent in ("to_q", "to_kv", "proj_in"):
            return P(None, "model")  # af2lint: rank=2 — column parallel: shard output dim
        if parent in ("to_out", "proj_out"):
            return P("model", None)  # af2lint: rank=2 — row parallel: shard input dim
    if leaf_name == "b" and parent in ("to_q", "to_kv", "proj_in"):
        return P("model")
    if parent == "compress":
        # conv kernel (k, in_per_group, out) / bias (out,): shard out
        if leaf_name == "w":
            return P(None, None, "model")  # af2lint: rank=3 — (k, in_per_group, out)
        if leaf_name == "b":
            return P("model")
    return P()


def param_spec(path, leaf, *, tp: bool) -> P:
    """PartitionSpec for a param (or optimizer-state) leaf."""
    if not hasattr(leaf, "ndim"):
        return P()
    names = _path_names(path)
    if not tp:
        return P()
    spec = _tp_spec(names, leaf)
    base_rank = {"w": 2, "b": 1, "table": 2, "scale": 1, "bias": 1}.get(
        names[-1] if names else "", None
    )
    if names and names[-2:-1] == ("compress",) and names[-1] == "w":
        base_rank = 3
    if base_rank is not None and leaf.ndim == base_rank + 1:
        # depth-stacked (reversible trunk): leading depth axis is replicated
        spec = P(None, *spec)
    return spec


def state_shardings(mesh: Mesh, state: Any, *, tp: bool = True):
    """NamedShardings for a full train state (params + opt state + step)."""
    has_model = tp and "model" in mesh.axis_names
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(
            mesh, param_spec(path, leaf, tp=has_model)
        ),
        state,
    )


def batch_shardings(mesh: Mesh, batch: Any, *, microbatched: bool = True):
    """Shard the per-device batch axis over "data". With `microbatched`,
    leaves are (accum, b, ...) and axis 1 is the batch axis."""
    axis = 1 if microbatched else 0

    def spec(leaf):
        parts = [None] * leaf.ndim
        if "data" in mesh.axis_names and leaf.ndim > axis:
            parts[axis] = "data"
        return NamedSharding(mesh, P(*parts))

    return jax.tree_util.tree_map(spec, batch)


def replicated(mesh: Mesh):
    return NamedSharding(mesh, P())
