"""Pass 5 — overlap-aware collective-schedule verification.

The overlap layer (parallel/overlap.py, parallel/sequence.py,
training/harness.py `make_axis_accum_train_step`) claims its collectives
ride UNDER compute instead of fencing it. That claim is structural — it
is visible in the lowered program — and checking it must not need a live
(and chronically wedged) chip. This pass lowers each overlapped program
for the TPU target on the CPU host (`jax.export`, the
scripts/check_mosaic_lowering.py route, on a subprocess-provisioned
8-device virtual platform) and asserts the schedule on the StableHLO
text:

  * expected collective COUNTS — the double-buffered ring carries
    exactly one extra static ppermute site (prefetch) and the overlapped
    DP step exactly one extra all-reduce site per bucket (the in-loop
    reduction), so a refactor that silently drops the overlap changes
    the counts;
  * the FENCE property — a collective whose results transitively feed a
    `dot_general` in the same function serializes that compute behind
    the wire. Overlapped ring programs must have ZERO fenced
    collective-permutes (the permuted block is consumed by the NEXT
    iteration, via the loop carry, never by this iteration's dots);
    the overlapped DP step must place its in-loop all-reduces so no
    dot depends on them;
  * the self-check — the pass also lowers each SYNCHRONOUS twin and
    asserts the fence detector still CATCHES it (fenced permutes > 0 /
    no in-loop all-reduce). If a JAX upgrade changes the lowering shape
    enough to blind the detector, the pass fails loudly instead of
    rubber-stamping overlapped programs.

SSA analysis is per-function and does not propagate through control-flow
ops (`stablehlo.while` results conflate loop carries: the prefetch hop
legitimately feeds the LATER iterations through the carry — that is the
overlap, not a fence). `jnp.where`-style outlined helpers (`func.call`)
propagate like ordinary ops.

CLI: part of ``python -m alphafold2_tpu.analysis --strict`` (pass name
``overlap``); skipped for file-scoped invocations like the smoke pass.
Fixtures: tests/test_overlap.py lowers a deliberately re-serialized
schedule and asserts this pass's checker flags it.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import subprocess
import sys
from typing import Dict, List, Sequence, Tuple

from alphafold2_tpu.analysis.common import Finding

PASS = "overlap"

# StableHLO collective ops, keyed by the short name used in reports
COLLECTIVES = {
    "stablehlo.collective_permute": "collective_permute",
    "stablehlo.all_reduce": "all_reduce",
    "stablehlo.all_to_all": "all_to_all",
    "stablehlo.all_gather": "all_gather",
}

# control-flow ops whose results conflate region-carried values: a dot
# consuming a while RESULT does not depend on any particular in-body op
_BARRIERS = {"stablehlo.while", "stablehlo.if", "stablehlo.case"}

_FUNC_RE = re.compile(r"\s*func\.func\b.*@([\w$.]+)")
_RES_RE = re.compile(r"^(%[\w.]+)(?::\d+)?\s*=\s*(.*)$")
_OP_RE = re.compile(r'"?([a-z_]+\.[a-z_.]+|func\.call|call)"?')
_VAL_RE = re.compile(r"%[A-Za-z0-9_.]+")
_CALLEE_RE = re.compile(r"\bfunc\.call\s+@([\w$.]+)")


def module_functions(text: str) -> List[Tuple[str, List[str]]]:
    """Split an MLIR module into (function_name, body_lines) chunks."""
    out: List[Tuple[str, List[str]]] = []
    name, lines = None, []
    for line in text.splitlines():
        m = _FUNC_RE.match(line)
        if m:
            if name is not None:
                out.append((name, lines))
            name, lines = m.group(1), []
        elif name is not None:
            lines.append(line)
    if name is not None:
        out.append((name, lines))
    return out


def _parse_ops(lines: Sequence[str]):
    """(ops, defs): ops = [(opname, results, operands)] in program order;
    defs maps each SSA result name to its defining op index. One op per
    line (the StableHLO pretty-printer's format)."""
    ops: List[Tuple[str, List[str], List[str]]] = []
    defs: Dict[str, int] = {}
    for line in lines:
        s = line.strip()
        if not s or s.startswith(("//", "}", "^")):
            continue
        results: List[str] = []
        rhs = s
        m = _RES_RE.match(s)
        if m:
            results = [m.group(1)]
            rhs = m.group(2)
        om = _OP_RE.search(rhs)
        if not om:
            continue
        opname = om.group(1)
        operands = [v.split("#")[0] for v in _VAL_RE.findall(rhs)]
        ops.append((opname, results, operands))
        for r in results:
            defs[r.split("#")[0]] = len(ops) - 1
    return ops, defs


def _fenced_in_function(lines: Sequence[str]) -> Dict[str, int]:
    """Per collective kind: how many of this function's collectives
    transitively feed a dot_general in the SAME function (= fence the
    compute). Propagation stops at control-flow ops (loop carries)."""
    ops, defs = _parse_ops(lines)
    coll_idx = {
        j: COLLECTIVES[op]
        for j, (op, _, _) in enumerate(ops)
        if op in COLLECTIVES
    }
    fenced: Dict[str, set] = {}
    for j, (op, _res, operands) in enumerate(ops):
        if op != "stablehlo.dot_general":
            continue
        seen: set = set()
        stack = list(operands)
        while stack:
            v = stack.pop()
            if v in seen:
                continue
            seen.add(v)
            d = defs.get(v)
            if d is None:
                continue
            dop = ops[d][0]
            if d in coll_idx:
                fenced.setdefault(coll_idx[d], set()).add(d)
            if dop in _BARRIERS:
                continue  # do not walk through loop carries
            stack.extend(ops[d][2])
    return {k: len(v) for k, v in fenced.items()}


def _loop_scope_lines(text: str) -> Tuple[List[str], List[str]]:
    """(loop_lines, callees): every line inside a `stablehlo.while`
    region, plus the names of functions `func.call`'d from there (scan
    and fori_loop bodies are outlined as closed_call functions)."""
    loop_lines: List[str] = []
    # stack entries: [start_depth, region_opened] — a while's regions
    # (`cond { ... } do { ... }`) open on LATER lines, so an entry only
    # pops once the depth has risen above start and come back
    depth_stack: List[List] = []
    depth = 0
    for line in text.splitlines():
        starting = "stablehlo.while" in line
        if depth_stack:
            loop_lines.append(line)
        depth += line.count("{") - line.count("}")
        if starting:
            depth_stack.append([depth, False])
        for entry in depth_stack:
            if depth > entry[0]:
                entry[1] = True
        while depth_stack and depth_stack[-1][1] and depth <= depth_stack[-1][0]:
            depth_stack.pop()
    callees = sorted(set(_CALLEE_RE.findall("\n".join(loop_lines))))
    return loop_lines, callees


@dataclasses.dataclass
class ScheduleStats:
    """Structural census of one lowered program's collective schedule."""

    counts: Dict[str, int]        # whole-module collective counts
    fenced: Dict[str, int]        # collectives feeding same-function dots
    loop_counts: Dict[str, int]   # collectives inside loop bodies
    loop_dots: int                # dot_generals inside loop bodies
    dots: int                     # whole-module dot_generals


def analyze_schedule(text: str) -> ScheduleStats:
    counts = {short: text.count(full) for full, short in COLLECTIVES.items()}
    fenced: Dict[str, int] = {}
    for _name, lines in module_functions(text):
        for k, v in _fenced_in_function(lines).items():
            fenced[k] = fenced.get(k, 0) + v
    loop_lines, callees = _loop_scope_lines(text)
    # closure over outlined loop bodies (one hop of calls covers the
    # closed_call pattern; walk further calls for nested scans)
    funcs = dict(module_functions(text))
    pending, seen = list(callees), set()
    while pending:
        c = pending.pop()
        if c in seen or c not in funcs:
            continue
        seen.add(c)
        loop_lines.extend(funcs[c])
        pending.extend(_CALLEE_RE.findall("\n".join(funcs[c])))
    loop_text = "\n".join(loop_lines)
    loop_counts = {
        short: loop_text.count(full) for full, short in COLLECTIVES.items()
    }
    return ScheduleStats(
        counts=counts,
        fenced=fenced,
        loop_counts=loop_counts,
        loop_dots=loop_text.count("stablehlo.dot_general"),
        dots=text.count("stablehlo.dot_general"),
    )


# --- schedule expectations --------------------------------------------------


def check_overlapped_ring(stats: ScheduleStats, expected_permutes: int) -> List[str]:
    """The double-buffered ring: exact ppermute count (prefetch + in-loop
    sites), no permute fencing a dot, and real compute present."""
    problems = []
    got = stats.counts.get("collective_permute", 0)
    if got != expected_permutes:
        problems.append(
            f"expected {expected_permutes} collective-permutes "
            f"(prefetch + loop-body sites), found {got}"
        )
    f = stats.fenced.get("collective_permute", 0)
    if f:
        problems.append(
            f"{f} collective-permute(s) feed a dot_general in the same "
            "function — the ring schedule is (re)serialized: transfers "
            "fence the block compute instead of hiding under it"
        )
    if stats.dots == 0:
        problems.append("no dot_general in module — nothing to overlap "
                        "(wrong program under test)")
    return problems


def check_serialized_ring_detected(stats: ScheduleStats) -> List[str]:
    """Self-check on the synchronous twin: the fence detector must fire."""
    if stats.fenced.get("collective_permute", 0) == 0:
        return [
            "fence detector failed to flag the SYNCHRONOUS ring schedule "
            "— the lowering shape changed and the overlap assertions "
            "above are no longer trustworthy"
        ]
    return []


def check_overlapped_dp(stats: ScheduleStats, n_buckets: int) -> List[str]:
    """The backward-overlapped DP step: per-bucket all-reduce inside the
    accumulation loop (2B+1 sites total: B in-loop + B flush + 1 loss),
    none fencing the microbatch fwd/bwd dots."""
    problems = []
    expect_total = 2 * n_buckets + 1
    got = stats.counts.get("all_reduce", 0)
    if got != expect_total:
        problems.append(
            f"expected {expect_total} all-reduces "
            f"({n_buckets} in-loop + {n_buckets} flush + 1 loss), found {got}"
        )
    in_loop = stats.loop_counts.get("all_reduce", 0)
    if in_loop < n_buckets:
        problems.append(
            f"only {in_loop} all-reduce(s) inside the accumulation loop "
            f"(expected {n_buckets}) — the gradient reduction does not "
            "overlap the next microbatch's fwd/bwd"
        )
    if stats.loop_dots == 0:
        problems.append("no dot_general inside the accumulation loop — "
                        "wrong program under test")
    f = stats.fenced.get("all_reduce", 0)
    if f:
        problems.append(
            f"{f} all-reduce(s) feed a dot_general in the same function "
            "— the reduction fences compute"
        )
    return problems


def check_serialized_dp_detected(stats: ScheduleStats, n_buckets: int) -> List[str]:
    """Self-check on the synchronous DP twin: no in-loop reduction, and
    exactly the post-scan flush + loss all-reduces."""
    problems = []
    if stats.loop_counts.get("all_reduce", 0) != 0:
        problems.append(
            "synchronous DP arm unexpectedly has in-loop all-reduces — "
            "the A/B pair no longer isolates the overlap"
        )
    expect = n_buckets + 1
    got = stats.counts.get("all_reduce", 0)
    if got != expect:
        problems.append(
            f"synchronous DP arm: expected {expect} all-reduces "
            f"({n_buckets} flush + 1 loss), found {got}"
        )
    return problems


def check_overlapped_sp_trunk(stats: ScheduleStats, expected_permutes: int) -> List[str]:
    """The SP trunk's ring cross-attention under the overlapped schedule:
    same fence property as the plain ring; the trunk's OTHER collectives
    (all_to_all grid transposes, the tied-row logit psum) are semantic
    barriers and are allowed to fence."""
    problems = []
    got = stats.counts.get("collective_permute", 0)
    if got != expected_permutes:
        problems.append(
            f"expected {expected_permutes} collective-permutes in the SP "
            f"trunk (the ring cross-attention sites), found {got}"
        )
    f = stats.fenced.get("collective_permute", 0)
    if f:
        problems.append(
            f"{f} ring collective-permute(s) fence a dot_general — the "
            "SP trunk's ring cross-attention is (re)serialized"
        )
    return problems


# --- the worker (runs on a subprocess-provisioned 8-device platform) --------

_N_DEV = 8


def worker_main() -> None:
    """Build + export every overlapped program and its synchronous twin,
    run the schedule checks, print one JSON line of problems. Assumes the
    virtual CPU platform is already in force (the pass runner's
    subprocess sets it up)."""
    import jax

    if len(jax.devices()) < _N_DEV:
        print(json.dumps({"fatal": (
            f"virtual platform provisioning failed: need {_N_DEV} "
            f"devices, have {len(jax.devices())}")}))
        return
    from jax import export as jexport
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from alphafold2_tpu import compat
    from alphafold2_tpu.models import Alphafold2Config
    from alphafold2_tpu.models.trunk import trunk_layer_init
    from alphafold2_tpu.parallel import (
        make_dp_overlap_train_step,
        make_mesh,
        plan_buckets,
        ring_attention,
        sp_trunk_apply,
    )
    from alphafold2_tpu.training.harness import TrainConfig, train_state_init

    problems: Dict[str, List[str]] = {}

    def export_text(fn, *args) -> str:
        return jexport.export(jax.jit(fn), platforms=["tpu"])(
            *args
        ).mlir_module()

    # --- ring attention (XLA streaming hops), both schedules ---------------
    mesh = make_mesh({"seq": _N_DEV})
    spec = P(None, "seq", None, None)
    qs = jax.ShapeDtypeStruct((1, 4 * _N_DEV, 2, 8), jnp.float32)
    ms = jax.ShapeDtypeStruct((1, 4 * _N_DEV), jnp.bool_)

    def ring(overlap):
        return compat.shard_map(
            lambda q, k, v, m: ring_attention(
                q, k, v, "seq", mask=m, use_kernel=False, overlap=overlap
            ),
            mesh=mesh,
            in_specs=(spec, spec, spec, P(None, "seq")),
            out_specs=spec,
        )

    txt = export_text(ring(True), qs, qs, qs, ms)
    # 3 permuted buffers (k, v, bias) x 2 static sites (prefetch + body)
    problems["ring_overlap"] = check_overlapped_ring(
        analyze_schedule(txt), expected_permutes=6
    )
    txt = export_text(ring(False), qs, qs, qs, ms)
    problems["ring_sync_detector"] = check_serialized_ring_detected(
        analyze_schedule(txt)
    )

    # --- SP trunk (ring cross-attention inside the full layer) -------------
    sp_cfg = Alphafold2Config(
        dim=16, depth=1, heads=2, dim_head=8, max_seq_len=32,
        msa_tie_row_attn=True,
    )
    layers = [trunk_layer_init(jax.random.PRNGKey(0), sp_cfg)]
    xs = jax.ShapeDtypeStruct((1, 2 * _N_DEV, 2 * _N_DEV, 16), jnp.float32)
    mss = jax.ShapeDtypeStruct((1, _N_DEV, 8, 16), jnp.float32)
    txt = export_text(
        lambda x, m: sp_trunk_apply(
            layers, sp_cfg, x, m, mesh, overlap=True
        ),
        xs, mss,
    )
    # one ring cross-attention (MSA<-pair) x 3 buffers x 2 sites
    problems["sp_trunk_overlap"] = check_overlapped_sp_trunk(
        analyze_schedule(txt), expected_permutes=6
    )

    # --- the SERVING-shaped SP program (ISSUE 14) --------------------------
    # exactly what a ServingEngine bucket executable runs under the SP arm:
    # embedder -> sp_seq trunk -> distogram head -> MDS, batch-shaped. The
    # ring cross-attention inside must keep the same overlap property the
    # bare trunk has — the serving wrapper (padding, masks, the replicated
    # head) must not reserialize the schedule.
    from alphafold2_tpu.models import alphafold2_init
    from alphafold2_tpu.serving.pipeline import predict_structure
    from alphafold2_tpu.serving.sp_arm import make_sp_apply_fn

    # depth 2, NOT 1: the distogram head consumes only the pair stream,
    # so the LAST layer's MSA<-pair ring is dead code the compiler
    # eliminates — layer 1's ring is the live site under test (exactly
    # the structure of any real multi-layer serving model)
    serve_cfg = Alphafold2Config(dim=16, depth=2, heads=2, dim_head=8,
                                 max_seq_len=2 * _N_DEV)
    serve_params = alphafold2_init(jax.random.PRNGKey(1), serve_cfg)
    sp_apply = make_sp_apply_fn(mesh, "sp_seq", axis_name="seq",
                                overlap=True)
    tok = jax.ShapeDtypeStruct((2, 2 * _N_DEV), jnp.int32)
    msk = jax.ShapeDtypeStruct((2, 2 * _N_DEV), jnp.bool_)
    msa_s = jax.ShapeDtypeStruct((2, _N_DEV, 2 * _N_DEV), jnp.int32)
    msam_s = jax.ShapeDtypeStruct((2, _N_DEV, 2 * _N_DEV), jnp.bool_)
    txt = export_text(
        lambda p, t, m, ms, mm: predict_structure(
            p, serve_cfg, t, mask=m, msa=ms, msa_mask=mm,
            mds_iters=2, mds_init="classical", model_apply_fn=sp_apply,
        ),
        serve_params, tok, msk, msa_s, msam_s,
    )
    # same single ring site as the bare trunk: 3 buffers x 2 static sites
    problems["serving_sp_overlap"] = check_overlapped_sp_trunk(
        analyze_schedule(txt), expected_permutes=6
    )

    # --- DP-overlap train step, both schedules -----------------------------
    dp_mesh = make_mesh({"data": _N_DEV})
    cfg = Alphafold2Config(dim=16, depth=1, heads=2, dim_head=8,
                           max_seq_len=16)
    tcfg = TrainConfig(learning_rate=1e-3, grad_accum=3)
    batch = {
        "seq": jax.ShapeDtypeStruct((3, _N_DEV, 8), jnp.int32),
        "mask": jax.ShapeDtypeStruct((3, _N_DEV, 8), jnp.bool_),
        "coords": jax.ShapeDtypeStruct((3, _N_DEV, 8, 3), jnp.float32),
    }
    state = jax.eval_shape(
        lambda k: train_state_init(k, cfg, tcfg), jax.random.PRNGKey(0)
    )
    n_buckets = len(plan_buckets(state["params"])[1])
    for overlap, key, check in (
        (True, "dp_overlap",
         lambda s: check_overlapped_dp(s, n_buckets)),
        (False, "dp_sync_detector",
         lambda s: check_serialized_dp_detected(s, n_buckets)),
    ):
        step, _ = make_dp_overlap_train_step(
            cfg, tcfg, dp_mesh, batch, overlap=overlap, donate_state=False
        )
        txt = jexport.export(step, platforms=["tpu"])(
            state, batch
        ).mlir_module()
        problems[key] = check(analyze_schedule(txt))

    # --- DP-overlap on the MULTI-HOST mesh shape ---------------------------
    # A pod's mesh is hybrid: DP on the outer (DCN, cross-host) axis, the
    # bandwidth-hungry strategy on the inner (ICI) axis. The overlap
    # claim must survive THAT lowering — the all-reduce subgroups become
    # strided over the inner axis, which is exactly the reshuffle that
    # could silently reserialize the schedule. Same program, same
    # structural assertions, hybrid {"data": 2} x {"model": 4} mesh
    # (process-spanning in production; device-count-identical here, the
    # lowering is what's under test).
    from alphafold2_tpu.parallel import hybrid_mesh

    hb_mesh = hybrid_mesh({"data": 2}, {"model": 4})
    hb_batch = {
        "seq": jax.ShapeDtypeStruct((3, 2, 8), jnp.int32),
        "mask": jax.ShapeDtypeStruct((3, 2, 8), jnp.bool_),
        "coords": jax.ShapeDtypeStruct((3, 2, 8, 3), jnp.float32),
    }
    step, _ = make_dp_overlap_train_step(
        cfg, tcfg, hb_mesh, hb_batch, overlap=True, donate_state=False
    )
    txt = jexport.export(step, platforms=["tpu"])(
        state, hb_batch
    ).mlir_module()
    problems["dp_overlap_hybrid_mesh"] = check_overlapped_dp(
        analyze_schedule(txt), n_buckets
    )

    print(json.dumps({"problems": problems}))


def run(root=None, files=None, **_) -> List[Finding]:
    """Pass entry point: verify the overlap schedules on a subprocess
    (the virtual multi-device platform must be set before jax's backend
    initializes, which the calling process usually already did)."""
    del root, files
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env = dict(os.environ)
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = f"{flags} --xla_force_host_platform_device_count={_N_DEV}"
    env["XLA_FLAGS"] = flags.strip()
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("JAX_PLATFORM_NAME", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = os.pathsep.join(
        [repo_root] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else [])
    )
    src = "alphafold2_tpu/analysis/overlap_lint.py"
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "from alphafold2_tpu.analysis.overlap_lint import worker_main; "
             "worker_main()"],
            env=env, capture_output=True, text=True, timeout=900,
        )
    except subprocess.TimeoutExpired:
        return [Finding(PASS, "OVL000", src, 1,
                        "overlap-lint worker timed out (900s)")]
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-3:]
        return [Finding(PASS, "OVL000", src, 1,
                        f"worker failed rc={proc.returncode}: "
                        f"{' | '.join(tail)[:300]}")]
    payload = None
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            payload = json.loads(line)
            break
        except ValueError:
            continue
    if payload is None:
        return [Finding(PASS, "OVL000", src, 1,
                        "no JSON verdict in worker output")]
    if "fatal" in payload:
        return [Finding(PASS, "OVL000", src, 1, payload["fatal"])]
    findings = []
    for program, probs in sorted(payload.get("problems", {}).items()):
        for p in probs:
            findings.append(Finding(PASS, "OVL001", program, 0, p))
    return findings
