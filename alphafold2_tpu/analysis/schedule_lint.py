"""Pass 6 — branch-parallel trunk-schedule verification.

The branch-parallel trunk schedule (models/trunk.py
`branch_parallel_layer_apply`, cfg.trunk_schedule="branch_parallel")
claims each layer's pair track and MSA track are two data-INDEPENDENT
branches that join only at the cross-attention exchange. Like the
overlap pass, the claim is structural — visible in the lowered program —
and must be checkable without a live chip. This pass lowers each
branch-parallel trunk variant for the TPU target on the CPU host
(`jax.export` on a subprocess-provisioned 8-device virtual platform, the
overlap_lint.py route) and asserts on the StableHLO text:

  * every layer emits exactly one JOIN marker — a multi-operand
    `stablehlo.optimization_barrier` (models/trunk.py `schedule_join`) —
    so a refactor that silently drops the schedule changes the count;
  * at every join, the operands' backward slices (the ops each branch
    computed since the previous join) partition into >= 2 groups sharing
    NO heavy op (dot_general / convolution / reduce): the branches are
    really data-independent before the join. Slice propagation stops at
    control-flow results (loop carries) and at OTHER barriers (each join
    scopes its own pre-join region), and linkage counts only heavy ops —
    CSE'd constants and scalar plumbing shared by both branches are not
    dependence;
  * the SERIAL trunk emits no barrier at all — the marker uniquely
    identifies the branch-parallel arm;
  * the self-check: a deliberately SERIALIZED twin
    (`branch_parallel_layer_apply(serialize_twin=True)` — the MSA branch
    arithmetically coupled behind the pair branch) must be FLAGGED by
    the same check. If a JAX upgrade changes the lowering enough to
    blind the detector, the pass fails loudly instead of rubber-stamping
    branch-parallel programs.

CLI: part of ``python -m alphafold2_tpu.analysis --strict`` (pass name
``schedule``); skipped for file-scoped invocations like the smoke pass.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Dict, List, Sequence, Set

from alphafold2_tpu.analysis.common import Finding
from alphafold2_tpu.analysis.overlap_lint import (
    _BARRIERS,
    _parse_ops,
    module_functions,
)

PASS = "schedule"

_JOIN_OP = "stablehlo.optimization_barrier"

# ops that constitute real compute: two branches sharing one of these in
# their pre-join slices are data-dependent. Constants, broadcasts, and
# elementwise plumbing (which CSE can legitimately share) never count.
_HEAVY = {
    "stablehlo.dot_general",
    "stablehlo.convolution",
    "stablehlo.reduce",
    "stablehlo.reduce_window",
}


def _backward_slice(ops, defs, seeds: Sequence[str]) -> Set[int]:
    """Op indices transitively feeding `seeds` within one function.

    Stops at control-flow results (a dot consuming a while result does
    not depend on any particular in-body op — overlap_lint semantics)
    AND at other optimization_barriers: each join scopes the region since
    the previous join, which is exactly the branch region the schedule
    claims independent."""
    seen_vals: Set[str] = set()
    out: Set[int] = set()
    stack = list(seeds)
    while stack:
        v = stack.pop()
        if v in seen_vals:
            continue
        seen_vals.add(v)
        d = defs.get(v)
        if d is None:
            continue
        out.add(d)
        dop = ops[d][0]
        if dop in _BARRIERS or dop == _JOIN_OP:
            continue
        stack.extend(ops[d][2])
    return out


def _components(link_sets: List[Set[int]]) -> int:
    """Connected components over operands, linked by shared heavy ops."""
    n = len(link_sets)
    parent = list(range(n))

    def find(a):
        while parent[a] != a:
            parent[a] = parent[parent[a]]
            a = parent[a]
        return a

    for i in range(n):
        for j in range(i + 1, n):
            if link_sets[i] & link_sets[j]:
                parent[find(i)] = find(j)
    return len({find(i) for i in range(n)})


def analyze_joins(text: str):
    """[(function, op_index, n_operands, n_components)] for every
    multi-operand optimization_barrier in the module."""
    joins = []
    for fname, lines in module_functions(text):
        ops, defs = _parse_ops(lines)
        for idx, (opname, _res, operands) in enumerate(ops):
            if opname != _JOIN_OP or len(operands) < 2:
                continue
            slices = [_backward_slice(ops, defs, [v]) for v in operands]
            heavy = [
                {d for d in s if ops[d][0] in _HEAVY} for s in slices
            ]
            joins.append((fname, idx, len(operands), _components(heavy)))
    return joins


def check_branch_parallel(text: str, min_joins: int) -> List[str]:
    """The clean branch-parallel program: the expected number of join
    markers, every one of them with truly independent branches."""
    problems = []
    joins = analyze_joins(text)
    if len(joins) < min_joins:
        problems.append(
            f"expected >= {min_joins} schedule-join marker(s) "
            f"(one per layer / scanned body), found {len(joins)} — the "
            "branch-parallel schedule is not being emitted"
        )
    for fname, idx, n_ops, n_comp in joins:
        if n_comp < 2:
            problems.append(
                f"join at {fname}#{idx} ({n_ops} operands): branch slices "
                "share heavy compute — the branches are data-dependent "
                "before the join (schedule serialized)"
            )
    return problems


def check_serial_unmarked(text: str) -> List[str]:
    """The serial reference arm must carry NO join markers: the barrier
    uniquely identifies the branch-parallel schedule."""
    if _JOIN_OP in text:
        return [
            "serial-schedule program contains optimization_barrier(s) — "
            "the join marker no longer uniquely identifies the "
            "branch-parallel arm"
        ]
    return []


def check_serialized_twin_detected(text: str) -> List[str]:
    """Self-check: the deliberately serialized twin must be flagged."""
    joins = analyze_joins(text)
    if not joins:
        return [
            "serialized twin lowered with no join marker — wrong program "
            "under test"
        ]
    if all(n_comp >= 2 for _, _, _, n_comp in joins):
        return [
            "detector failed to flag the SERIALIZED twin schedule — the "
            "lowering shape changed and the branch-independence "
            "assertions above are no longer trustworthy"
        ]
    return []


# --- the worker (runs on a subprocess-provisioned 8-device platform) --------

_N_DEV = 8


def worker_main() -> None:
    """Build + export every branch-parallel trunk variant (and the serial
    + serialized-twin fixtures), run the schedule checks, print one JSON
    line of problems."""
    import jax

    if len(jax.devices()) < _N_DEV:
        print(json.dumps({"fatal": (
            f"virtual platform provisioning failed: need {_N_DEV} "
            f"devices, have {len(jax.devices())}")}))
        return
    import dataclasses

    from jax import export as jexport
    import jax.numpy as jnp

    from alphafold2_tpu.models import Alphafold2Config
    from alphafold2_tpu.models.reversible import (
        reversible_trunk_apply,
        reversible_trunk_init,
    )
    from alphafold2_tpu.models.trunk import (
        branch_parallel_layer_apply,
        sequential_trunk_apply,
        trunk_layer_init,
    )
    from alphafold2_tpu.parallel import make_mesh, sp_trunk_apply

    problems: Dict[str, List[str]] = {}

    def export_text(fn, *args) -> str:
        return jexport.export(jax.jit(fn), platforms=["tpu"])(
            *args
        ).mlir_module()

    depth = 2
    cfg = Alphafold2Config(
        dim=16, depth=depth, heads=2, dim_head=8, max_seq_len=64,
        msa_tie_row_attn=True,
    )
    cfg_bp = dataclasses.replace(cfg, trunk_schedule="branch_parallel")
    keys = jax.random.split(jax.random.PRNGKey(0), 2 + depth)
    layers = [trunk_layer_init(k, cfg) for k in keys[2:]]
    n = 2 * _N_DEV
    xs = jax.ShapeDtypeStruct((1, n, n, cfg.dim), jnp.float32)
    ms = jax.ShapeDtypeStruct((1, _N_DEV, n, cfg.dim), jnp.float32)
    x = jax.random.normal(keys[0], (1, n, n, cfg.dim))
    m = jax.random.normal(keys[1], (1, _N_DEV, n, cfg.dim))

    # --- sequential trunk: branch arm marked + independent; serial bare --
    txt = export_text(
        lambda a, b: sequential_trunk_apply(layers, cfg_bp, a, b), xs, ms
    )
    # unrolled: one join per layer
    problems["sequential_branch_parallel"] = check_branch_parallel(
        txt, min_joins=depth
    )
    txt = export_text(
        lambda a, b: sequential_trunk_apply(layers, cfg, a, b), xs, ms
    )
    problems["sequential_serial_unmarked"] = check_serial_unmarked(txt)

    # --- detector self-check: the serialized twin must be flagged --------
    txt = export_text(
        lambda a, b: branch_parallel_layer_apply(
            layers[0], cfg_bp, a, b, serialize_twin=True
        ),
        xs, ms,
    )
    problems["serialized_twin_detector"] = check_serialized_twin_detected(txt)

    # --- reversible trunk: the join rides inside the scanned body --------
    rcfg_bp = dataclasses.replace(cfg_bp, reversible=True)
    stacked = reversible_trunk_init(jax.random.PRNGKey(1), rcfg_bp)
    txt = export_text(
        lambda a, b: reversible_trunk_apply(stacked, rcfg_bp, a, b), xs, ms
    )
    problems["reversible_branch_parallel"] = check_branch_parallel(
        txt, min_joins=1
    )

    # --- SP trunk: branches (incl. their collectives) join under
    # shard_map, mapping onto disjoint mesh work -------------------------
    mesh = make_mesh({"seq": _N_DEV})
    txt = export_text(
        lambda a, b: sp_trunk_apply(layers[:1], cfg_bp, a, b, mesh), x, m
    )
    problems["sp_branch_parallel"] = check_branch_parallel(txt, min_joins=1)

    print(json.dumps({"problems": problems}))


def run(root=None, files=None, **_) -> List[Finding]:
    """Pass entry point: verify the branch schedules on a subprocess (the
    virtual multi-device platform must be set before jax's backend
    initializes)."""
    del root, files
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env = dict(os.environ)
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = f"{flags} --xla_force_host_platform_device_count={_N_DEV}"
    env["XLA_FLAGS"] = flags.strip()
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("JAX_PLATFORM_NAME", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = os.pathsep.join(
        [repo_root] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else [])
    )
    src = "alphafold2_tpu/analysis/schedule_lint.py"
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "from alphafold2_tpu.analysis.schedule_lint import worker_main; "
             "worker_main()"],
            env=env, capture_output=True, text=True, timeout=900,
        )
    except subprocess.TimeoutExpired:
        return [Finding(PASS, "SCH000", src, 1,
                        "schedule-lint worker timed out (900s)")]
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-3:]
        return [Finding(PASS, "SCH000", src, 1,
                        f"worker failed rc={proc.returncode}: "
                        f"{' | '.join(tail)[:300]}")]
    payload = None
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            payload = json.loads(line)
            break
        except ValueError:
            continue
    if payload is None:
        return [Finding(PASS, "SCH000", src, 1,
                        "no JSON verdict in worker output")]
    if "fatal" in payload:
        return [Finding(PASS, "SCH000", src, 1, payload["fatal"])]
    findings = []
    for program, probs in sorted(payload.get("problems", {}).items()):
        for p in probs:
            findings.append(Finding(PASS, "SCH001", program, 0, p))
    return findings
