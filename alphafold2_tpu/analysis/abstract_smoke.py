"""Pass 4 — abstract-interpretation smoke.

`jax.eval_shape` every public op, the full model, and every
`training/presets.py` tier under abstract inputs. eval_shape runs the
whole trace — imports, shape arithmetic, dtype promotion, custom-VJP
wiring, Pallas kernel construction — without compiling or executing a
single FLOP, so an import-time or trace-time regression (exactly the
class that had the seed suite red) surfaces in seconds on a laptop
instead of minutes into a paid TPU reservation.

Each target is a named thunk; a target that raises becomes one SMOKE001
finding carrying the exception head. Registered targets:

  ops.*        flash / blockwise / dense / axial attention, feed-forward,
               the kernel dispatch registry (ops.dispatch)
  model.*      alphafold2 init+apply at smoke shapes
  serving.*    the serving pipeline + the engine's bucketed batch shapes
  reliability.* fault-plan parse/roundtrip, circuit-breaker transitions,
               verified-checkpoint save/restore (host-side construction
               checks — same gate, no shapes involved)
  telemetry.*  span tracer + chrome export, metric registry + Prometheus
               round-trip, regression-gate verdicts, goodput ledger +
               federation + loss-curve gate (host-side, like
               reliability.*)
  presets.*    e2e train-state init for every tier; full e2e loss (fwd +
              structure module) at smoke shapes

Add a target when adding a public op: append to `_targets()`.
"""

from __future__ import annotations

import json
import traceback
from typing import Callable, Dict, List

from alphafold2_tpu.analysis.common import Finding

PASS = "smoke"


def _targets() -> Dict[str, Callable[[], None]]:
    """name -> thunk that eval_shapes one surface (raises on breakage)."""
    import jax
    import jax.numpy as jnp

    import numpy as np

    key = jax.random.PRNGKey(0)
    f32 = jnp.float32

    def abstract(shape, dtype=f32):
        return jax.ShapeDtypeStruct(shape, dtype)

    targets: Dict[str, Callable[[], None]] = {}

    def register(name):
        def deco(fn):
            targets[name] = fn
            return fn

        return deco

    # --- ops ---------------------------------------------------------------
    @register("ops.flash_attention_tpu")
    def _flash():
        from alphafold2_tpu.ops.flash_kernel import flash_attention_tpu

        jax.eval_shape(
            lambda q, k, v, b: flash_attention_tpu(q, k, v, b, 0.35, qb=128, kb=128),
            abstract((2, 16, 8)), abstract((2, 24, 8)), abstract((2, 24, 8)),
            abstract((2, 24)),
        )

    @register("ops.flash_attention_lse")
    def _flash_lse():
        from alphafold2_tpu.ops.flash_kernel import flash_attention_lse

        jax.eval_shape(
            lambda q, k, v, b: flash_attention_lse(q, k, v, b, 0.35, qb=128, kb=128),
            abstract((2, 16, 8)), abstract((2, 16, 8)), abstract((2, 16, 8)),
            abstract((2, 16)),
        )

    @register("ops.flash_attention_grad")
    def _flash_grad():
        from alphafold2_tpu.ops.flash_kernel import flash_attention_tpu

        jax.eval_shape(
            jax.grad(
                lambda q, k, v, b: flash_attention_tpu(
                    q, k, v, b, 0.35, qb=128, kb=128
                ).sum(),
                argnums=(0, 1, 2),
            ),
            abstract((2, 16, 8)), abstract((2, 16, 8)), abstract((2, 16, 8)),
            abstract((2, 16)),
        )

    @register("ops.flash_attention_fused")
    def _flash_fused():
        from alphafold2_tpu.ops.flash_kernel import flash_attention_fused

        # 2-D pair-bias tiles + in-kernel output gate, fwd and grads
        # (incl. the real d_bias / d_gate cotangents)
        jax.eval_shape(
            jax.grad(
                lambda q, k, v, b, g: flash_attention_fused(
                    q, k, v, b, 0.35, gate=g, qb=128, kb=128
                ).sum(),
                argnums=(0, 1, 2, 3, 4),
            ),
            abstract((2, 16, 8)), abstract((2, 24, 8)), abstract((2, 24, 8)),
            abstract((2, 16, 24)), abstract((2, 16, 8)),
        )

    @register("ops.blockwise_attention")
    def _blockwise():
        from alphafold2_tpu.ops.flash import blockwise_attention

        jax.eval_shape(
            lambda q, k, v: blockwise_attention(q, k, v),
            abstract((2, 32, 4, 8)), abstract((2, 32, 4, 8)),
            abstract((2, 32, 4, 8)),
        )

    @register("ops.attention")
    def _attention():
        from alphafold2_tpu.ops import AttentionConfig, attention_apply, attention_init

        cfg = AttentionConfig(dim=32, heads=4, dim_head=8)
        params = jax.eval_shape(lambda k: attention_init(k, cfg), key)
        jax.eval_shape(
            lambda p, x: attention_apply(p, cfg, x), params, abstract((2, 12, 32))
        )

    @register("ops.axial_attention")
    def _axial():
        from alphafold2_tpu.ops import (
            AttentionConfig,
            axial_attention_apply,
            axial_attention_init,
        )

        cfg = AttentionConfig(dim=32, heads=4, dim_head=8)
        params = jax.eval_shape(lambda k: axial_attention_init(k, cfg), key)
        jax.eval_shape(
            lambda p, x: axial_attention_apply(p, cfg, x),
            params,
            abstract((1, 8, 8, 32)),
        )

    @register("ops.feed_forward")
    def _ff():
        from alphafold2_tpu.ops import feed_forward_apply, feed_forward_init

        params = jax.eval_shape(lambda k: feed_forward_init(k, 32), key)
        jax.eval_shape(
            lambda p, x: feed_forward_apply(p, x), params, abstract((2, 12, 32))
        )

    @register("ops.block_sparse_attention")
    def _sparse():
        from alphafold2_tpu.ops.sparse import SparseConfig, block_sparse_attention

        scfg = SparseConfig(block_size=16)
        jax.eval_shape(
            lambda q, k, v: block_sparse_attention(q, k, v, scfg=scfg),
            abstract((1, 64, 4, 8)), abstract((1, 64, 4, 8)),
            abstract((1, 64, 4, 8)),
        )

    # --- model -------------------------------------------------------------
    @register("model.alphafold2")
    def _model():
        from alphafold2_tpu.models import (
            Alphafold2Config,
            alphafold2_apply,
            alphafold2_init,
        )

        cfg = Alphafold2Config(
            dim=32, depth=1, heads=4, dim_head=8, max_seq_len=64
        )
        params = jax.eval_shape(lambda k: alphafold2_init(k, cfg), key)
        seq = abstract((1, 12), jnp.int32)
        jax.eval_shape(lambda p, s: alphafold2_apply(p, cfg, s), params, seq)

    @register("model.trunk_branch_parallel")
    def _trunk_branch_parallel():
        from alphafold2_tpu.models import Alphafold2Config
        from alphafold2_tpu.models.trunk import (
            sequential_trunk_apply,
            trunk_layer_init,
        )

        # the branch-parallel schedule with a gated attention config —
        # the two tentpole arms of PR 7 trace together
        cfg = Alphafold2Config(
            dim=32, depth=2, heads=4, dim_head=8, max_seq_len=64,
            trunk_schedule="branch_parallel", attn_gate=True,
        )
        layers = jax.eval_shape(
            lambda k: [
                trunk_layer_init(kk, cfg) for kk in jax.random.split(k, 2)
            ],
            key,
        )
        jax.eval_shape(
            lambda ls, x, m: sequential_trunk_apply(ls, cfg, x, m),
            layers, abstract((1, 8, 8, 32)), abstract((1, 4, 8, 32)),
        )

    # --- serving -------------------------------------------------------------
    @register("serving.pipeline")
    def _serving_pipeline():
        from alphafold2_tpu.models import (
            Alphafold2Config,
            alphafold2_init,
        )
        from alphafold2_tpu.serving.pipeline import predict_structure

        cfg = Alphafold2Config(dim=32, depth=1, heads=4, dim_head=8,
                               max_seq_len=64)
        params = jax.eval_shape(lambda k: alphafold2_init(k, cfg), key)
        jax.eval_shape(
            lambda p, t, m: predict_structure(
                p, cfg, t, mask=m, mds_iters=2, mds_init="classical"
            ),
            params, abstract((2, 12), jnp.int32), abstract((2, 12), jnp.bool_),
        )

    @register("serving.engine.bucketed_batch")
    def _serving_bucketed():
        # the exact shape family the engine AOT-compiles: a (max_batch,
        # bucket) padded batch for every ladder rung, msa-free and with a
        # fixed-row MSA stream (ServingConfig.msa_rows)
        from alphafold2_tpu.models import (
            Alphafold2Config,
            alphafold2_init,
        )
        from alphafold2_tpu.serving.bucketing import BucketLadder
        from alphafold2_tpu.serving.pipeline import predict_structure

        cfg = Alphafold2Config(dim=32, depth=1, heads=4, dim_head=8,
                               max_seq_len=32)
        params = jax.eval_shape(lambda k: alphafold2_init(k, cfg), key)
        ladder = BucketLadder((16, 32))
        assert ladder.bucket_for(9) == 16
        for bucket in ladder.buckets:
            jax.eval_shape(
                lambda p, t, m: predict_structure(
                    p, cfg, t, mask=m, mds_iters=2, mds_init="classical"
                ),
                params, abstract((4, bucket), jnp.int32),
                abstract((4, bucket), jnp.bool_),
            )
        jax.eval_shape(
            lambda p, t, m, ms, mm: predict_structure(
                p, cfg, t, mask=m, msa=ms, msa_mask=mm,
                mds_iters=2, mds_init="classical"
            ),
            params, abstract((4, 16), jnp.int32), abstract((4, 16), jnp.bool_),
            abstract((4, 4, 16), jnp.int32), abstract((4, 4, 16), jnp.bool_),
        )

    @register("ops.quant_matmul")
    def _quant_matmul():
        # per-channel PTQ + the fused-dequant Pallas kernel construction
        # (use_kernel=True traces the pallas_call), the XLA dequant
        # reference arm, and a stacked reversible-layout quantize
        from alphafold2_tpu.ops.quant import quant_matmul, quantize_weight

        def run(x, w):
            qw, scale = quantize_weight(w)
            return quant_matmul(x, qw, scale, use_kernel=True)

        jax.eval_shape(run, abstract((6, 4, 32)), abstract((32, 16)))

        def run_xla(x, w):
            qw, scale = quantize_weight(w, per_channel=False)
            return quant_matmul(x, qw, scale, use_kernel=False,
                                dtype=jnp.bfloat16)

        jax.eval_shape(run_xla, abstract((4, 32)), abstract((32, 16)))
        jax.eval_shape(
            lambda w: quantize_weight(w), abstract((3, 32, 16))
        )

    @register("ops.dispatch")
    def _dispatch():
        # registry construction + resolution for every op on every
        # platform (host arithmetic — no tracing), the introspection
        # table/tag, and a dispatch-routed op under eval_shape: the
        # whole resolve path must be trace-safe (ints and env only, no
        # device reads inside jit)
        from alphafold2_tpu.ops import dispatch
        from alphafold2_tpu.ops.flash import flash_attention

        for op in dispatch.ops():
            spec = dispatch.get(op)
            arm_names = set(spec.arm_names())
            assert "xla_ref" in arm_names, op
            for platform in ("tpu", "gpu", "cpu"):
                arm = dispatch.resolve(op, request="auto",
                                       platform=platform, **spec.probe)
                assert arm in arm_names, (op, platform, arm)
            # forcing the reference arm never depends on shape support
            assert dispatch.resolve(op, request=False, platform="cpu",
                                    **spec.probe) == "xla_ref"
        assert dispatch.resolution_table()
        assert dispatch.resolution_tag().startswith("dispatch[")
        jax.eval_shape(
            lambda q, k, v: flash_attention(q, k, v, use_kernel="auto"),
            abstract((2, 16, 2, 8)), abstract((2, 24, 2, 8)),
            abstract((2, 24, 2, 8)),
        )

    @register("serving.quant_residency")
    def _quant_residency():
        # the engine's build-time precision seam: int8 config -> PTQ tree
        # (fp32 master untouched) + residency info, second build under
        # the same tag served from the process cache (host-side
        # construction check, like reliability.*)
        import dataclasses

        from alphafold2_tpu.models import Alphafold2Config, alphafold2_init
        from alphafold2_tpu.serving.quant_residency import (
            clear_residency_cache,
            resident_params,
        )

        tiny = Alphafold2Config(dim=16, depth=1, heads=2, dim_head=8,
                                max_seq_len=16)
        params = alphafold2_init(key, tiny)
        clear_residency_cache()
        try:
            same, info = resident_params(params, tiny)
            assert same is params and info["weight_dtype"] == "f32"
            int8_cfg = dataclasses.replace(tiny, weight_dtype="int8")
            tree, info = resident_params(params, int8_cfg)
            assert info["weight_bytes"] < info["fp32_weight_bytes"]
            assert not info["cached"]
            tree2, info2 = resident_params(params, int8_cfg)
            assert tree2 is tree and info2["cached"]
        finally:
            clear_residency_cache()

    @register("serving.fleet")
    def _serving_fleet():
        # fleet round trip over stub engines: admission -> dispatch ->
        # completion callback -> client future, plus clean shutdown. An
        # import- or wiring-time break in the fleet/admission layer must
        # surface here, not first in a paid chaos replay
        import numpy as np

        from alphafold2_tpu.models import Alphafold2Config
        from alphafold2_tpu.serving import (
            FleetConfig,
            ServingConfig,
            ServingEngine,
            ServingFleet,
        )

        tiny = Alphafold2Config(dim=16, depth=1, heads=2, dim_head=8,
                                max_seq_len=16)

        class Stub(ServingEngine):
            def _call_executable(self, bucket, tokens, mask, msa=None,
                                 msa_mask=None):
                B, Lb = tokens.shape
                return {
                    "coords": np.zeros((B, Lb, 3), np.float32),
                    "confidence": np.full((B, Lb), 0.5, np.float32),
                    "stress": np.zeros((B,), np.float32),
                }

        fleet = ServingFleet(
            {}, tiny,
            ServingConfig(buckets=(8, 16), max_batch=2, max_wait_s=0.0,
                          cache_capacity=0),
            FleetConfig(replicas=2, probe_interval_s=0),
            engine_factory=lambda n, c, h: Stub({}, tiny, c, fault_hook=h),
        )
        try:
            res = fleet.predict("ACDEF", timeout=30)
            assert res.coords.shape == (5, 3) and res.replica in ("r0", "r1")
            assert fleet.stats()["requests"]["completed"] == 1
        finally:
            fleet.shutdown()

    @register("serving.featurize")
    def _serving_featurize():
        # featurize tier round trip: the pure featurize function agrees
        # with its own re-run (determinism is the tier's bit-exactness
        # contract), and a 1-worker pool carries a job through submit ->
        # worker -> on_done -> clean shutdown
        import threading

        import numpy as np

        from alphafold2_tpu.serving import (
            BucketLadder,
            FeaturizeConfig,
            FeaturizePool,
            featurize_request,
        )

        ladder = BucketLadder((8, 16))
        a = featurize_request("acdef", ladder=ladder)
        b = featurize_request("ACDEF", ladder=ladder)
        assert a.seq == b.seq == "ACDEF" and a.bucket == 8
        np.testing.assert_array_equal(a.tokens, b.tokens)

        done = threading.Event()
        out = {}
        pool = FeaturizePool(FeaturizeConfig(workers=1), ladder)
        try:
            pool.submit("ACDEF", on_done=lambda bun, exc: (
                out.update(bundle=bun, exc=exc), done.set()))
            assert done.wait(30)
            assert out["exc"] is None and out["bundle"].bucket == 8
            assert pool.stats()["requests"]["completed"] == 1
        finally:
            pool.shutdown()

    @register("serving.autoscale")
    def _serving_autoscale():
        # autoscaler state machine over a stub fleet with an injected
        # clock: policy validation, a sustained-signal scale-up, and an
        # idle scale-down after the hysteresis window — no threads
        from alphafold2_tpu.serving import ReplicaAutoscaler, ScalePolicy
        from alphafold2_tpu.telemetry import MetricRegistry

        registry = MetricRegistry()
        depth = registry.gauge("fleet_queue_depth")
        occ = registry.gauge("fleet_occupancy")

        class StubFleet:
            _closed = False

            def __init__(self):
                self.registry = registry
                self.n = 1

            def sample_gauges(self):
                pass

            def replica_count(self):
                return self.n

            def add_replica(self):
                self.n += 1
                return f"r{self.n - 1}"

            def remove_replica(self, name=None):
                self.n -= 1
                return f"r{self.n}"

        fleet = StubFleet()
        t = [0.0]
        scaler = ReplicaAutoscaler(
            fleet,
            ScalePolicy(min_replicas=1, max_replicas=2, up_sustain=2,
                        down_sustain=2, up_cooldown_s=0.0,
                        down_cooldown_s=5.0),
            registry=registry, clock=lambda: t[0])
        depth.set(4), occ.set(2.0)
        for _ in range(2):
            scaler.tick()
            t[0] += 1.0
        assert fleet.n == 2, fleet.n
        depth.set(0), occ.set(0.0)
        t[0] += 10.0  # past the hysteresis window
        for _ in range(2):
            scaler.tick()
            t[0] += 1.0
        assert fleet.n == 1, fleet.n
        assert len(scaler.scale_events()) == 2

    @register("serving.sp_pipeline")
    def _serving_sp_pipeline():
        # the SP serving arm's executable under eval_shape (ISSUE 14):
        # the chip-free schedule plan picks per bucket, and the planned
        # SP apply traces the bucket-shaped serving forward over a
        # model-axis mesh — both dynamic-axial cuts
        from alphafold2_tpu.models import Alphafold2Config, alphafold2_init
        from alphafold2_tpu.parallel import make_mesh
        from alphafold2_tpu.serving import sp_arm
        from alphafold2_tpu.serving.pipeline import predict_structure

        cfg = Alphafold2Config(dim=16, depth=1, heads=2, dim_head=8,
                               max_seq_len=32)
        params = jax.eval_shape(lambda k: alphafold2_init(k, cfg), key)
        # planning is pure shard-count arithmetic + eval_shape pricing
        plan = sp_arm.plan_bucket_schedules(
            cfg, buckets=(16, 32), batch=2, msa_rows=0, shards=2,
            hbm_bytes=float(1 << 40), overrides={32: "sp_seq"})
        assert plan[32].schedule == "sp_seq"
        assert plan[16].schedule == "dense"
        assert plan[32].pair_bytes < sp_arm.schedule_residency(
            cfg, bucket=32, batch=2, msa_rows=0, schedule="dense",
            shards=2).pair_bytes
        # the trace itself runs at whatever mesh this host can provision
        # (the tier-1 suite forces 8 virtual CPU devices; a bare CLI run
        # degrades to a 1-shard mesh — same program, same trace checks)
        mesh = make_mesh({"sp": 2 if len(jax.devices()) >= 2 else 1})
        sp_apply = sp_arm.make_sp_apply_fn(mesh, "sp_seq")
        jax.eval_shape(
            lambda p, t, m: predict_structure(
                p, cfg, t, mask=m, mds_iters=2, mds_init="classical",
                model_apply_fn=sp_apply,
            ),
            params, abstract((2, 32), jnp.int32), abstract((2, 32), jnp.bool_),
        )
        msa_apply = sp_arm.make_sp_apply_fn(mesh, "sp_msa")
        jax.eval_shape(
            lambda p, t, m, ms, mm: predict_structure(
                p, cfg, t, mask=m, msa=ms, msa_mask=mm,
                mds_iters=2, mds_init="classical",
                model_apply_fn=msa_apply,
            ),
            params, abstract((2, 16), jnp.int32), abstract((2, 16), jnp.bool_),
            abstract((2, 2, 16), jnp.int32), abstract((2, 2, 16), jnp.bool_),
        )

    @register("serving.capability_routing")
    def _capability_routing():
        # the length-adaptive router over stub engines (ISSUE 14): short
        # work lands on the cheap pool, long work on the wide pool, and a
        # sequence past every pool's ceiling sheds with the sharp
        # sequence_too_long code instead of dying in dispatch
        import numpy as np

        from alphafold2_tpu.models import Alphafold2Config
        from alphafold2_tpu.serving import (
            FleetConfig,
            PoolSpec,
            SequenceTooLongError,
            ServingConfig,
            ServingEngine,
            ServingFleet,
        )

        tiny = Alphafold2Config(dim=16, depth=1, heads=2, dim_head=8,
                                max_seq_len=32)

        class Stub(ServingEngine):
            def _call_executable(self, bucket, tokens, mask, msa=None,
                                 msa_mask=None):
                B, Lb = tokens.shape
                return {
                    "coords": np.zeros((B, Lb, 3), np.float32),
                    "confidence": np.full((B, Lb), 0.5, np.float32),
                    "stress": np.zeros((B,), np.float32),
                }

        fleet = ServingFleet(
            {}, tiny,
            ServingConfig(buckets=(8, 16), max_batch=2, max_wait_s=0.0,
                          cache_capacity=0),
            FleetConfig(probe_interval_s=0, pools=(
                PoolSpec("short", buckets=(8, 16)),
                PoolSpec("long", buckets=(8, 16, 32)),
            )),
            engine_factory=lambda n, c, h: Stub({}, tiny, c, fault_hook=h),
        )
        try:
            a = fleet.predict("ACDEFGHIKL", timeout=30)          # L=10
            b = fleet.predict("ACDEFGHIKLMNPQRSTVWYACDEF", timeout=30)
            st = fleet.stats()
            assert st["replicas"][a.replica]["pool"] == "short"
            assert st["replicas"][b.replica]["pool"] == "long"
            assert st["replicas"][b.replica]["capability"]["max_len"] == 32
            try:
                fleet.submit("A" * 40)
                raise AssertionError("40-mer must shed: no pool ceiling "
                                     "covers it")
            except SequenceTooLongError as e:
                assert e.code == "sequence_too_long"
            assert fleet.stats()["shed"]["too_long"] == 1
        finally:
            fleet.shutdown()

    # --- reliability --------------------------------------------------------
    # host-side subsystems: no shapes to eval, but the same failure class —
    # an import- or construction-time regression in the chaos layer must
    # surface in the seconds-cheap gate, not first in a paid chaos run
    @register("reliability.fault_plan")
    def _fault_plan():
        from alphafold2_tpu.reliability import (
            FAULT_KINDS,
            REPLICA_FAULT_KINDS,
            FaultPlan,
        )

        plan = FaultPlan.from_json(json.dumps({
            "seed": 7,
            "faults": [
                {"kind": k, "at": i,
                 **({"replica": "r0"} if k in REPLICA_FAULT_KINDS else {})}
                for i, k in enumerate(FAULT_KINDS)
            ],
        }))
        assert FaultPlan.from_json(plan.to_json()) == plan
        inj = plan.injector()
        assert not inj.exhausted()
        # hook factories build (incl. the fleet replica-scoped hook)
        inj.checkpoint_hook(), inj.serving_hook(), inj.replica_hook("r0")

    @register("reliability.breaker")
    def _breaker():
        from alphafold2_tpu.reliability import CircuitBreaker, CircuitState

        t = [0.0]
        b = CircuitBreaker(threshold=2, reset_s=5.0, clock=lambda: t[0])
        assert b.allow()
        b.record_failure(), b.record_failure()
        assert b.state is CircuitState.OPEN and not b.allow()
        t[0] = 6.0
        assert b.allow() and not b.allow()  # one half-open probe
        b.record_success()
        assert b.state is CircuitState.CLOSED

    @register("reliability.health")
    def _health():
        from alphafold2_tpu.reliability import HealthMonitor, ReplicaState

        t = [0.0]
        seen = []
        up = [False]  # replica answers probes only once "repaired"
        mon = HealthMonitor(probe_interval_s=1.0, reprobe_interval_s=1.0,
                            fail_threshold=2, clock=lambda: t[0])
        mon.register("r0", probe=lambda: up[0],
                     on_drain=lambda n, why: seen.append(("drain", n)),
                     on_reinstate=lambda n: seen.append(("up", n)))
        # dispatch evidence drains at threshold, on the next tick
        assert not mon.record_failure("r0")
        assert mon.record_failure("r0")
        assert mon.state("r0") is ReplicaState.DOWN
        mon.tick(now=0.0)
        assert seen == [("drain", "r0")]
        assert mon.state("r0") is ReplicaState.DOWN  # re-probe still failing
        up[0] = True
        t[0] = 2.0
        mon.tick()  # re-probe succeeds -> reinstated
        assert mon.state("r0") is ReplicaState.HEALTHY
        assert seen[-1] == ("up", "r0")

    @register("reliability.verified_checkpoint")
    def _verified_ckpt():
        import tempfile

        import numpy as np

        from alphafold2_tpu.training.checkpoint import VerifiedCheckpointManager

        with tempfile.TemporaryDirectory() as d:
            mgr = VerifiedCheckpointManager(d)
            state = {"params": {"w": np.arange(4.0)},
                     "step": np.asarray(1, np.int32)}
            assert mgr.save(state, force=True)
            assert mgr.latest_step() == 1
            out = mgr.restore()
            np.testing.assert_array_equal(out["params"]["w"], state["params"]["w"])

    # --- telemetry ----------------------------------------------------------
    # host-side like the reliability targets: an import- or construction-
    # time break in the observability layer must surface in the cheap gate
    @register("telemetry.trace")
    def _telemetry_trace():
        from alphafold2_tpu.telemetry import NULL_TRACER, Tracer

        t = Tracer()
        with t.span("outer", cat="smoke", k=1):
            with t.span("inner"):
                pass
        events = t.chrome_trace()["traceEvents"]
        assert any(e["ph"] == "X" and e["name"] == "outer" for e in events)
        assert t.summary()["inner"]["count"] == 1
        # disabled fast path returns the shared no-op singleton
        assert NULL_TRACER.span("a") is NULL_TRACER.span("b")

    @register("telemetry.registry")
    def _telemetry_registry():
        from alphafold2_tpu.telemetry import (
            MetricRegistry,
            parse_prometheus_text,
        )

        r = MetricRegistry()
        r.counter("smoke_total", outcome="ok").inc(2)
        r.gauge("smoke_depth").set(3)
        r.histogram("smoke_seconds").observe(0.5)
        parsed = parse_prometheus_text(r.to_prometheus())
        assert parsed[("smoke_total", (("outcome", "ok"),))] == 2.0

    @register("telemetry.check")
    def _telemetry_check():
        from alphafold2_tpu.telemetry.check import check

        ok, _ = check({"metric": "smoke_steps_per_sec", "value": 1.0},
                      {"metric": "smoke_steps_per_sec", "value": 1.0})
        assert ok
        bad, rows = check({"metric": "smoke_steps_per_sec", "value": 0.5},
                          {"metric": "smoke_steps_per_sec", "value": 1.0})
        assert not bad and rows[0]["status"] == "regressed"

    @register("telemetry.goodput")
    def _telemetry_goodput():
        # host-side like the other telemetry targets: ledger exclusive-
        # time accounting + sums-to-wall invariant, detector firing, and
        # a gather-injected 2-process federation round-trip
        from alphafold2_tpu.telemetry import MetricRegistry
        from alphafold2_tpu.telemetry.goodput import (
            FederatedRegistryView,
            GoodputLedger,
            MetricFederation,
            StragglerDetector,
        )
        from alphafold2_tpu.telemetry.registry import parse_prometheus_text

        clk = [0.0]
        reg = MetricRegistry()
        led = GoodputLedger(reg, clock=lambda: clk[0])
        with led.account("data_fetch"):
            clk[0] += 1.0
        with led.account("compile"):
            clk[0] += 2.0
            with led.account("assembly"):  # nested: exclusive-time split
                clk[0] += 0.5
        led.step_complete(0)
        clk[0] += 0.5  # uncategorized -> idle
        snap = led.snapshot()
        # against the LIVE wall (snapshot's wall_s is the bucket sum, a
        # tautology); the injected clock is frozen so this is exact
        assert abs(sum(snap["buckets"].values()) - led.wall()) < 1e-9
        assert abs(snap["buckets"]["assembly"] - 0.5) < 1e-9
        assert abs(snap["buckets"]["compile"] - 2.0) < 1e-9
        assert led.step_bucket() == "step"  # compiled after the first step

        class _Rec:
            kinds: list = []

            def incident(self, kind, **attrs):
                self.kinds.append(kind)

        det = StragglerDetector(recorder=_Rec(), registry=reg,
                                patience=2, min_seconds=0.001)
        for s in range(2):
            det.observe_pod(s, [
                {"process": 0, "step_s": 0.1, "fetch_s": 0.01},
                {"process": 1, "step_s": 0.5, "fetch_s": 0.01},
            ])
        assert "train_straggler" in _Rec.kinds

        store = {}

        def gather_for(i):
            def gather(b):
                store[i] = b
                return [store.get(0, b), store.get(1, b)]

            return gather

        other = MetricRegistry()
        other.gauge("train_goodput_ratio").set(0.7)
        f0 = MetricFederation(reg, process_index=0, every=1,
                              gather_fn=gather_for(0))
        MetricFederation(other, process_index=1, every=1,
                         gather_fn=gather_for(1)).tick(0)
        f0.tick(0)
        text = FederatedRegistryView(reg, f0).to_prometheus()
        procs = {dict(k[1]).get("process")
                 for k in parse_prometheus_text(text)
                 if k[0] == "train_goodput_ratio"}
        assert procs == {"0", "1"}, procs

    @register("telemetry.cost_ledger")
    def _telemetry_cost_ledger():
        # host-side: the cost-plane algebra — analytic x measured join
        # over an int8 and an SP cell, derived chip-seconds/MFU, pool
        # service-rate model, publish round-trip
        from alphafold2_tpu.telemetry import MetricRegistry
        from alphafold2_tpu.telemetry.costs import ExecutableCostLedger

        reg = MetricRegistry()
        led = ExecutableCostLedger(reg)
        led.set_peak(1e12)
        k8 = led.register_cell(
            pool="short", bucket=256, schedule="dense",
            backend_arm="xla_ref", weight_dtype="int8",
            forward_flops=2e9, residency_bytes=1 << 28, max_batch=4)
        ksp = led.register_cell(
            pool="long", bucket=1024, schedule="sp_seq",
            backend_arm="pallas_tpu", weight_dtype="f32",
            forward_flops=8e10, residency_bytes=1 << 30, chips=8,
            max_batch=2)
        led.observe_batch(k8, device_seconds=0.1, requests=4)
        led.observe_batch(ksp, device_seconds=1.0, requests=2)
        rows = {(c["pool"], c["bucket"]): c for c in led.cells()}
        short = rows[("short", 256)]
        assert abs(short["chip_seconds_per_request"] - 0.1 / 4) < 1e-9
        assert abs(short["mfu"] - (4 * 2e9 / 0.1) / 1e12) < 1e-9
        long_ = rows[("long", 1024)]
        # the SP cell bills all 8 chips: 1.0s x 8 / 2 requests
        assert abs(long_["chip_seconds_per_request"] - 4.0) < 1e-9
        assert led.pool_rate_rps("short") == 40.0
        assert led.pool_rate_rps("unmeasured") is None
        led.publish()
        gauges = reg.snapshot()["gauges"]
        assert any(k.startswith("serve_chip_seconds_per_request")
                   for k in gauges), sorted(gauges)

    @register("serving.goodput")
    def _serving_goodput():
        # host-side: replica-second accounting, sums-to-wall via the
        # explicit idle remainder, probe overlap subtraction, publish
        from alphafold2_tpu.telemetry import MetricRegistry
        from alphafold2_tpu.telemetry.costs import ServeGoodputLedger

        clk = [0.0]
        reg = MetricRegistry()
        led = ServeGoodputLedger(reg, clock=lambda: clk[0])
        led.register("r0", "short")
        led.add("r0", "compile", 2.0)
        led.add("r0", "execute", 3.0)
        with led.probe_span("r0"):
            clk[0] += 1.0
            led.add("r0", "execute", 0.4)  # the probe's own dispatch
        clk[0] += 9.0
        totals = led.totals("r0")
        assert abs(totals["probe"] - 0.6) < 1e-9  # round trip minus inner
        assert abs(sum(totals.values()) - led.wall("r0")) < 1e-9
        snap = led.snapshot()
        assert abs(snap["replicas"]["r0"]["goodput_ratio"] - 3.4 / 10.0) \
            < 1e-9
        assert abs(snap["pools"]["short"]["goodput_ratio"] - 3.4 / 10.0) \
            < 1e-9
        led.publish()
        gauges = reg.snapshot()["gauges"]
        assert gauges['serve_goodput_ratio{pool="short",replica="r0"}'] \
            == snap["replicas"]["r0"]["goodput_ratio"]

    @register("telemetry.loss_curve_gate")
    def _telemetry_loss_curve():
        import os
        import tempfile

        from alphafold2_tpu.telemetry.check import check, load_loss_curve

        def write(vals):
            fd, path = tempfile.mkstemp(suffix=".jsonl")
            with os.fdopen(fd, "w") as fh:
                for i, v in enumerate(vals):
                    fh.write(json.dumps({"step": i, "loss": v}) + "\n")
            return path

        conv = write([3.0 / (1 + 0.2 * i) for i in range(40)])
        div = write([3.0 / (1 + 0.2 * i) + (0.2 * max(0, i - 20)) ** 1.5
                     for i in range(40)])
        try:
            ok, _ = check(load_loss_curve(conv), load_loss_curve(conv))
            assert ok
            bad, rows = check(load_loss_curve(div), load_loss_curve(conv))
            assert not bad
            assert any(r["metric"] == "loss_final"
                       and r["status"] == "regressed" for r in rows)
        finally:
            os.unlink(conv)
            os.unlink(div)

    # --- parallel / overlap -------------------------------------------------
    @register("parallel.partition_rules")
    def _partition_rules():
        # the registry matched over the REAL flagship train state
        # (eval_shape'd — depth-stacked reversible layout included):
        # raises on an unmatched leaf, a rank-incompatible rule, or a
        # registry/model drift — the same contract the sharding-lint
        # coverage pass enforces, kept here so `--files` smoke runs and
        # CI target lists exercise it too
        from jax.sharding import PartitionSpec

        from alphafold2_tpu.models import Alphafold2Config
        from alphafold2_tpu.parallel.rules import (
            match_partition_rules,
            partition_rules,
        )
        from alphafold2_tpu.training.harness import (
            TrainConfig,
            train_state_init,
        )

        cfg = Alphafold2Config(
            dim=16, depth=2, heads=2, dim_head=8, max_seq_len=32,
            reversible=True, msa_tie_row_attn=True,
            cross_attn_compress_ratio=2,
        )
        state = jax.eval_shape(
            lambda k: train_state_init(k, cfg, TrainConfig(grad_accum=1)),
            key,
        )
        specs = match_partition_rules(partition_rules(True), state)
        flat = jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: isinstance(x, PartitionSpec)
        )
        assert flat and all(isinstance(s, PartitionSpec) for s in flat)
        sharded = [s for s in flat if any(e is not None for e in s)]
        assert sharded, "TP registry produced no sharded specs"

    @register("parallel.overlap_bucketing")
    def _overlap_bucketing():
        import numpy as np  # module-level np is deleted after registration

        from alphafold2_tpu.parallel.overlap import (
            flatten_buckets,
            plan_buckets,
            unflatten_buckets,
        )

        tree = {
            "a": np.arange(6.0, dtype=np.float32).reshape(2, 3),
            "b": {"w": np.ones(17, np.float32),
                  "n": np.arange(4, dtype=np.int32)},
        }
        treedef, buckets = plan_buckets(tree, bucket_elems=8)
        covered = sorted(i for ix in buckets for i in ix)
        assert covered == list(range(3)), buckets
        out = unflatten_buckets(
            flatten_buckets(tree, buckets), tree, treedef, buckets
        )
        np.testing.assert_array_equal(np.asarray(out["a"]), tree["a"])
        np.testing.assert_array_equal(np.asarray(out["b"]["n"]),
                                      tree["b"]["n"])

    @register("parallel.axis_accum_step")
    def _axis_accum_step():
        # the DP-overlap step body traces under eval_shape with a dummy
        # axis env — catches pytree/bucket plumbing breaks without
        # needing the 8-device platform (the overlap pass covers the
        # lowered schedule itself)
        from alphafold2_tpu.models import Alphafold2Config
        from alphafold2_tpu.training.harness import (
            TrainConfig,
            make_axis_accum_train_step,
            train_state_init,
        )

        cfg = Alphafold2Config(dim=32, depth=1, heads=4, dim_head=8,
                               max_seq_len=32)
        tcfg = TrainConfig(grad_accum=2)
        step = make_axis_accum_train_step(cfg, tcfg,
                                          loss_fn=_distogram_loss(),
                                          axis_name="data")
        batch = {
            "seq": abstract((2, 1, 16), jnp.int32),
            "mask": abstract((2, 1, 16), jnp.bool_),
            "coords": abstract((2, 1, 16, 3)),
        }
        state = jax.eval_shape(
            lambda k: train_state_init(k, cfg, tcfg), key
        )

        def under_axis(state, batch):
            return step(state, batch, None)

        import functools

        jax.eval_shape(
            functools.partial(_with_dummy_axis, under_axis, "data"),
            state, batch,
        )

    def _distogram_loss():
        from alphafold2_tpu.training.harness import distogram_loss_fn

        return distogram_loss_fn

    def _with_dummy_axis(fn, axis_name, *args):
        # a single-shard vmapped axis gives lax.psum a bound axis name
        return jax.vmap(lambda _, a, b: fn(a, b), axis_name=axis_name,
                        in_axes=(0, None, None), out_axes=None)(
            jnp.zeros((1,)), *args)

    # --- training presets ---------------------------------------------------
    def _preset_init(tier):
        def thunk():
            from alphafold2_tpu.training.e2e import e2e_train_state_init
            from alphafold2_tpu.training.harness import TrainConfig
            from alphafold2_tpu.training.presets import north_star_e2e_config

            ecfg, _, _ = north_star_e2e_config(depth=2, tier=tier)
            tcfg = TrainConfig()
            jax.eval_shape(lambda k: e2e_train_state_init(k, ecfg, tcfg), key)

        return thunk

    for tier in ("smoke", "proportional", "north_star"):
        targets[f"presets.{tier}.init"] = _preset_init(tier)

    @register("presets.smoke.e2e_loss")
    def _e2e_loss():
        from alphafold2_tpu.training.e2e import (
            e2e_train_state_init,
            make_e2e_loss_fn,
        )
        from alphafold2_tpu.training.harness import TrainConfig
        from alphafold2_tpu.training.presets import north_star_e2e_config

        ecfg, crop, msa_rows = north_star_e2e_config(depth=2, tier="smoke")
        state = jax.eval_shape(
            lambda k: e2e_train_state_init(k, ecfg, TrainConfig()), key
        )
        loss_fn = make_e2e_loss_fn()
        batch = {
            "seq": abstract((1, crop), jnp.int32),
            "mask": abstract((1, crop), jnp.bool_),
            "coords": abstract((1, crop, 14, 3)),
            # the reversible trunk requires an MSA stream
            "msa": abstract((1, msa_rows, crop), jnp.int32),
            "msa_mask": abstract((1, msa_rows, crop), jnp.bool_),
        }
        jax.eval_shape(
            lambda p, b, k: loss_fn(p, ecfg, b, k), state["params"], batch, key
        )

    del np  # imported to fail fast when the env lacks it
    return targets


def run() -> List[Finding]:
    findings: List[Finding] = []
    try:
        targets = _targets()
    except Exception as e:  # registry construction itself failing is a finding
        findings.append(
            Finding(
                PASS,
                "SMOKE000",
                "alphafold2_tpu/analysis/abstract_smoke.py",
                1,
                f"smoke registry failed to build: {type(e).__name__}: {e}",
            )
        )
        return findings
    for name, thunk in targets.items():
        try:
            thunk()
        except Exception as e:
            tb = traceback.format_exc(limit=3).strip().splitlines()
            head = f"{type(e).__name__}: {e}".splitlines()[0][:300]
            findings.append(
                Finding(
                    PASS,
                    "SMOKE001",
                    name,
                    0,
                    f"eval_shape failed — {head} (tail: {tb[-1][:160]})",
                )
            )
    return findings
