"""The JAX API drift table: symbols that were renamed/moved between JAX
releases and therefore MUST resolve through `alphafold2_tpu/compat.py`.

Each row documents one rename so the compat linter can flag EITHER
spelling at a call site — code written against the old name breaks on new
JAX, code written against the new name breaks on old JAX (the seed's
actual failure: `pltpu.CompilerParams` on a 0.4.x image that only has
`TPUCompilerParams`, 20+ red tier-1 tests from two call sites).

Adding an entry when JAX renames something (docs/STATIC_ANALYSIS.md):
  1. resolve the name once in compat.py with a version-gated fallback;
  2. add a DriftEntry here with both spellings and the boundary version;
  3. `python -m alphafold2_tpu.analysis --strict` then flags every direct
     use of either spelling outside compat.py.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class DriftEntry:
    """One renamed symbol.

    attr_names: attribute spellings that identify the symbol at a call
        site (matched against the last attribute of a dotted access);
    full_names: dotted prefixes that also identify it (e.g. bare-module
        paths), matched exactly;
    keywords: call keywords that drifted along with the symbol;
    compat_name: how call sites should spell it;
    renamed_in: 'old -> new @ jax X.Y' documentation string.
    """

    attr_names: Tuple[str, ...]
    compat_name: str
    renamed_in: str
    full_names: Tuple[str, ...] = ()
    keywords: Tuple[str, ...] = ()
    note: Optional[str] = None


DRIFT_TABLE: Tuple[DriftEntry, ...] = (
    DriftEntry(
        attr_names=("TPUCompilerParams", "CompilerParams"),
        compat_name="compat.CompilerParams",
        renamed_in="pltpu.TPUCompilerParams -> pltpu.CompilerParams @ jax 0.6",
        note="same kwargs (dimension_semantics, ...); only the class name moved",
    ),
    DriftEntry(
        attr_names=("shard_map",),
        full_names=("jax.shard_map", "jax.experimental.shard_map.shard_map"),
        keywords=("check_vma", "check_rep"),
        compat_name="compat.shard_map",
        renamed_in=(
            "jax.experimental.shard_map.shard_map(check_rep=) -> "
            "jax.shard_map(check_vma=) @ jax 0.6"
        ),
    ),
    DriftEntry(
        attr_names=("typeof",),
        full_names=("jax.typeof",),
        compat_name="compat.typeof_vma",
        renamed_in="jax.typeof (and avals' .vma) introduced @ jax 0.7",
        note="pre-vma JAX has neither; compat returns an empty vma set there",
    ),
    DriftEntry(
        attr_names=(),
        full_names=(),
        keywords=("vma",),
        compat_name="compat.out_struct",
        renamed_in="ShapeDtypeStruct(vma=...) kwarg introduced @ jax 0.7",
        note="matched via the 'vma' call keyword on ShapeDtypeStruct calls",
    ),
    DriftEntry(
        attr_names=("pcast",),
        full_names=("jax.lax.pcast",),
        compat_name="compat.pcast",
        renamed_in="jax.lax.pcast introduced @ jax 0.7 (vma era)",
        note="identity on pre-vma JAX — there is no varying set to cast",
    ),
    DriftEntry(
        attr_names=("create_hybrid_device_mesh",),
        full_names=("jax.experimental.mesh_utils.create_hybrid_device_mesh",),
        compat_name="compat.create_hybrid_device_mesh",
        renamed_in="lives under jax.experimental.mesh_utils on all supported JAX",
        note="experimental-path import; routed through compat to keep the gate total",
    ),
)


def attr_index() -> dict:
    """{attribute_name: DriftEntry} for call-site matching."""
    out = {}
    for e in DRIFT_TABLE:
        for a in e.attr_names:
            out[a] = e
    return out


def keyword_index() -> dict:
    """{keyword: DriftEntry} for drifted call keywords."""
    out = {}
    for e in DRIFT_TABLE:
        for k in e.keywords:
            out[k] = e
    return out
