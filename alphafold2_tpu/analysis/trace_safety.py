"""Pass 2 — trace safety.

Finds the functions that run under a JAX trace — anything decorated with
or passed to `jax.jit` / `pjit` / `compat.shard_map` (and their
functools.partial forms) — walks every function reachable from them
through the module's local call graph, and flags Python-level operations
that are wrong on traced values:

  TRACE001  `print(...)` under trace: runs once at trace time, never on
            device — use `jax.debug.print`.
  TRACE002  host-numpy call (`np.*` / `numpy.*`) on a traced value:
            either crashes (TracerArrayConversionError) or silently
            constant-folds at trace time.
  TRACE003  data-dependent Python branch: `if x > 0:` on a traced value
            is a TracerBoolConversionError at trace time — use
            `jax.lax.cond` / `jnp.where`.
  TRACE004  Python concretization of a traced value (`float(x)`,
            `int(x)`, `bool(x)`, `x.item()`, `x.tolist()`).

Taint model (deliberately first-order): the parameters of a traced
function are traced; values assigned from expressions mentioning traced
names are traced. Metadata access is exempt — `.shape`, `.ndim`,
`.dtype`, `.size`, `len(x)`, `isinstance(x, ...)`, and `x is None`
checks are all static under tracing and legitimately drive Python
control flow. Config-like parameters (annotated or named `cfg`/`config`/
`*_config`, `self`, string/bool/int-annotated args) are not traced —
they are static argnums in practice; the pass errs on the side of NOT
flagging so `--strict` stays clean on legitimate code. Suppress a
deliberate trace-time effect with `# af2lint: disable=TRACE00x`.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set

from alphafold2_tpu.analysis.common import (
    Finding,
    dotted_name,
    filter_suppressed,
    iter_py_files,
    parse_file,
    rel,
    suppressed_lines,
)

PASS = "trace"

# callables whose function argument is traced
_TRACE_WRAPPERS = {
    "jax.jit",
    "jit",
    "jax.pjit",
    "pjit",
    "jax.experimental.pjit.pjit",
    "compat.shard_map",
    "shard_map",
    "jax.shard_map",
}

# attributes that read static metadata off a tracer (never concretize)
_STATIC_ATTRS = {
    "shape", "ndim", "dtype", "size", "aval", "sharding", "itemsize",
}

# parameter names that are configuration/static by convention, never arrays
_STATIC_PARAM_NAMES = {"self", "cls", "cfg", "config", "ecfg", "tcfg", "mesh"}

_NUMPY_ALIASES_DEFAULT = {"numpy"}

_CONCRETIZERS = {"float", "int", "bool", "complex"}
_CONCRETIZER_METHODS = {"item", "tolist", "__index__"}


def _func_name_of(call_func: ast.AST) -> Optional[str]:
    return dotted_name(call_func)


def _is_trace_wrapper(node: ast.AST) -> bool:
    """True if `node` (a decorator or call func) denotes jit/pjit/shard_map,
    directly or through functools.partial(jit, ...)."""
    if isinstance(node, ast.Call):
        name = _func_name_of(node.func)
        if name in _TRACE_WRAPPERS:
            return True
        if name in ("functools.partial", "partial") and node.args:
            return _is_trace_wrapper(node.args[0])
        return False
    return _func_name_of(node) in _TRACE_WRAPPERS


class _FunctionIndex(ast.NodeVisitor):
    """module-level (and class-method) def name -> node."""

    def __init__(self):
        self.defs: Dict[str, ast.AST] = {}

    def visit_FunctionDef(self, node):
        self.defs.setdefault(node.name, node)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef


def _entry_points(tree: ast.Module, defs: Dict[str, ast.AST]) -> List[ast.AST]:
    """Functions that run under trace: decorated with a trace wrapper, or
    passed (as the first argument) to a trace-wrapper call anywhere."""
    entries: List[ast.AST] = []
    seen: Set[int] = set()

    def add(fn):
        if fn is not None and id(fn) not in seen:
            seen.add(id(fn))
            entries.append(fn)

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_trace_wrapper(d) for d in node.decorator_list):
                add(node)
        elif isinstance(node, ast.Call) and _is_trace_wrapper(node):
            for a in node.args[:1]:
                if isinstance(a, ast.Lambda):
                    add(a)
                elif isinstance(a, ast.Name) and a.id in defs:
                    add(defs[a.id])
    return entries


def _reachable(entries: List[ast.AST], defs: Dict[str, ast.AST]) -> List[ast.AST]:
    """Transitive closure over same-module calls by bare name."""
    out: List[ast.AST] = []
    seen: Set[int] = set()
    work = list(entries)
    while work:
        fn = work.pop()
        if id(fn) in seen:
            continue
        seen.add(id(fn))
        out.append(fn)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                callee = defs.get(node.func.id)
                if callee is not None and id(callee) not in seen:
                    work.append(callee)
    return out


def _param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    names = [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]
    return names


def _static_param(p: ast.arg, name: str) -> bool:
    if name in _STATIC_PARAM_NAMES or name.endswith(("_config", "_cfg", "_name", "_fn")):
        return True
    ann = getattr(p, "annotation", None)
    if ann is not None:
        ann_name = dotted_name(ann) or (
            ast.unparse(ann) if hasattr(ast, "unparse") else ""
        )
        # scalar/static annotations -> static argnums by convention
        for s in ("int", "bool", "str", "float", "Config", "Mesh", "Optional[int]",
                  "Optional[str]", "Optional[bool]"):
            if ann_name == s or ann_name.endswith("." + s) or s in ann_name:
                return True
    return False


def _tainted_params(fn: ast.AST) -> Set[str]:
    a = fn.args
    out = set()
    for p in a.posonlyargs + a.args + a.kwonlyargs:
        if not _static_param(p, p.arg):
            out.add(p.arg)
    return out


class _TaintChecker(ast.NodeVisitor):
    """One pass over a traced function's body with first-order taint."""

    def __init__(self, path: str, numpy_aliases: Set[str], fn: ast.AST):
        self.path = path
        self.np_aliases = numpy_aliases
        self.fn = fn
        self.tainted: Set[str] = _tainted_params(fn)
        self.findings: List[Finding] = []

    # -- helpers ----------------------------------------------------------
    def _names_in(self, node: ast.AST) -> Set[str]:
        return {
            n.id
            for n in ast.walk(node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
        }

    def _is_tainted_expr(self, node: ast.AST) -> bool:
        """A bare tainted name used as an ARRAY (not via static metadata)."""
        for sub in ast.walk(node):
            if (
                isinstance(sub, ast.Name)
                and isinstance(sub.ctx, ast.Load)
                and sub.id in self.tainted
                and not self._only_static_use(node, sub)
            ):
                return True
        return False

    def _only_static_use(self, root: ast.AST, name_node: ast.Name) -> bool:
        """True when `name_node` appears only under a static-metadata
        context inside `root`: x.shape/..., len(x), isinstance(x, ...),
        `x is None` / `x is not None`."""
        parents = _parent_map(root)
        node = name_node
        parent = parents.get(id(node))
        while parent is not None:
            if isinstance(parent, ast.Attribute) and parent.attr in _STATIC_ATTRS:
                return True
            if isinstance(parent, ast.Call):
                fname = _func_name_of(parent.func)
                if fname in ("len", "isinstance", "type", "getattr", "hasattr"):
                    return True
            if isinstance(parent, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in parent.ops
            ):
                return True
            node = parent
            parent = parents.get(id(node))
        return False

    def _emit(self, code: str, line: int, msg: str):
        self.findings.append(Finding(PASS, code, self.path, line, msg))

    # -- taint propagation -------------------------------------------------
    def visit_Assign(self, node: ast.Assign):
        self.generic_visit(node)
        if self._is_tainted_expr(node.value):
            for t in node.targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        self.tainted.add(n.id)

    def visit_AugAssign(self, node: ast.AugAssign):
        self.generic_visit(node)
        if self._is_tainted_expr(node.value) and isinstance(node.target, ast.Name):
            self.tainted.add(node.target.id)

    # -- checks ------------------------------------------------------------
    def visit_Call(self, node: ast.Call):
        fname = _func_name_of(node.func) or ""
        root = fname.split(".", 1)[0] if fname else ""

        if fname == "print":
            self._emit(
                "TRACE001",
                node.lineno,
                "print() inside a traced function runs at trace time only; "
                "use jax.debug.print",
            )
        elif root in self.np_aliases and any(
            self._is_tainted_expr(a) for a in list(node.args) + [
                kw.value for kw in node.keywords
            ]
        ):
            self._emit(
                "TRACE002",
                node.lineno,
                f"host-numpy call {fname}() on a traced value — crashes or "
                "constant-folds at trace time; use jnp",
            )
        elif fname in _CONCRETIZERS and node.args and self._is_tainted_expr(node.args[0]):
            self._emit(
                "TRACE004",
                node.lineno,
                f"{fname}() concretizes a traced value "
                "(TracerBoolConversionError under jit)",
            )
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _CONCRETIZER_METHODS
            and self._is_tainted_expr(node.func.value)
        ):
            self._emit(
                "TRACE004",
                node.lineno,
                f".{node.func.attr}() concretizes a traced value",
            )
        self.generic_visit(node)

    def _check_branch(self, test: ast.AST, kind: str, line: int):
        if self._is_tainted_expr(test):
            self._emit(
                "TRACE003",
                line,
                f"data-dependent Python {kind} on a traced value — use "
                "jax.lax.cond / jnp.where (trace-time "
                "TracerBoolConversionError)",
            )

    def visit_If(self, node: ast.If):
        # `if bad: raise ...` is a legitimate trace-time validation guard:
        # on static quantities it runs at trace time; on a genuine tracer
        # it crashes loudly at trace time either way — no silent wrongness
        guard_only = all(
            isinstance(s, ast.Raise) for s in node.body
        ) and all(isinstance(s, ast.Raise) for s in node.orelse)
        if not guard_only:
            self._check_branch(node.test, "if", node.lineno)
        self.generic_visit(node)

    def visit_While(self, node: ast.While):
        self._check_branch(node.test, "while", node.lineno)
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert):
        self._check_branch(node.test, "assert", node.lineno)
        self.generic_visit(node)

    # nested defs get their own checker via reachability; don't double-walk
    def visit_FunctionDef(self, node):
        if node is self.fn:
            self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        if node is self.fn:
            self.generic_visit(node)


def _parent_map(root: ast.AST) -> Dict[int, ast.AST]:
    out: Dict[int, ast.AST] = {}
    for parent in ast.walk(root):
        for child in ast.iter_child_nodes(parent):
            out[id(child)] = parent
    return out


def _numpy_aliases(tree: ast.Module) -> Set[str]:
    aliases = set(_NUMPY_ALIASES_DEFAULT)
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    aliases.add(a.asname or "numpy")
    return aliases


def run(root, files: Optional[Sequence] = None) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_py_files(root, files):
        src, tree = parse_file(path)
        rpath = rel(path, root)
        if tree is None:
            continue  # compat pass reports the parse failure
        idx = _FunctionIndex()
        idx.visit(tree)
        entries = _entry_points(tree, idx.defs)
        if not entries:
            continue
        supp = suppressed_lines(src)
        np_aliases = _numpy_aliases(tree)
        for fn in _reachable(entries, idx.defs):
            checker = _TaintChecker(rpath, np_aliases, fn)
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for stmt in body:
                checker.visit(stmt)
            findings.extend(filter_suppressed(checker.findings, supp))
    return findings
