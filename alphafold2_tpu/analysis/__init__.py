"""af2lint: in-repo static analysis for a JAX codebase that cannot afford
runtime discovery of statically detectable breakage.

Nine passes, each a module in this package:

  * ``compat``   — AST linter: no `jax.experimental.*` access and no
                   drift-table symbol outside `alphafold2_tpu/compat.py`
                   (compat_lint.py, drift.py);
  * ``trace``    — trace-safety: walks functions reachable from
                   jit/pjit/shard_map entry points and flags Python side
                   effects, host-numpy calls on traced values, and
                   data-dependent Python branching (trace_safety.py);
  * ``sharding`` — PartitionSpec rank vs annotated rank, unknown /
                   duplicate mesh axis names against
                   parallel/mesh.py KNOWN_AXES (sharding_lint.py);
  * ``smoke``    — abstract interpretation: `jax.eval_shape` every public
                   op and training preset under abstract inputs — import-
                   and trace-time errors surface in seconds, zero FLOPs
                   (abstract_smoke.py);
  * ``overlap``  — collective-schedule verification: lowers the
                   overlapped multi-chip programs (double-buffered ring
                   attention, SP trunk, backward-overlapped DP step) via
                   `jax.export` and structurally asserts collectives
                   interleave with compute instead of fencing it
                   (overlap_lint.py);
  * ``schedule`` — branch-parallel trunk-schedule verification: lowers
                   the branch-parallel trunks (sequential, reversible,
                   SP) via `jax.export` and structurally asserts each
                   layer's pair/MSA branches are data-independent before
                   their join marker, with a serialized-twin detector
                   self-check (schedule_lint.py);
  * ``metrics``  — metric-name drift: every name registered at a
                   `.counter(`/`.gauge(`/`.histogram(` call site must be
                   documented in docs/OBSERVABILITY.md's inventory block
                   and vice versa (metrics_lint.py);
  * ``dispatch`` — kernel-dispatch monopoly: every registered hot op has
                   an `xla_ref` arm and a chip-free parity test, no
                   direct kernel imports outside ops/, no AF2_* env
                   reads outside ops/knobs.py (dispatch_lint.py);
  * ``concurrency`` — lock discipline over serving/telemetry/
                   reliability: shared attributes written from multiple
                   discovered thread entry points without a common lock
                   (CONC001), lock-order cycles in the cross-module
                   acquisition graph (CONC002), known-blocking calls
                   under a lock (CONC003), daemon threads whose call
                   graph reaches jax (CONC004); validated at runtime by
                   lock_runtime.py under the chaos acceptance tests
                   (concurrency_lint.py).

CLI: ``python -m alphafold2_tpu.analysis --strict`` (docs/STATIC_ANALYSIS.md).
"""

from __future__ import annotations

from alphafold2_tpu.analysis.common import Finding, iter_py_files, suppressed

__all__ = [
    "Finding",
    "PASSES",
    "PASS_SUMMARIES",
    "iter_py_files",
    "run_passes",
    "suppressed",
]


def _run_compat(root, files=None, **_):
    from alphafold2_tpu.analysis.compat_lint import run

    return run(root, files=files)


def _run_trace(root, files=None, **_):
    from alphafold2_tpu.analysis.trace_safety import run

    return run(root, files=files)


def _run_sharding(root, files=None, axes=None, **_):
    from alphafold2_tpu.analysis.sharding_lint import run

    return run(root, files=files, axes=axes)


def _run_smoke(root, **_):
    from alphafold2_tpu.analysis.abstract_smoke import run

    return run()


def _run_overlap(root, files=None, **_):
    from alphafold2_tpu.analysis.overlap_lint import run

    return run(root, files=files)


def _run_schedule(root, files=None, **_):
    from alphafold2_tpu.analysis.schedule_lint import run

    return run(root, files=files)


def _run_metrics(root, files=None, **_):
    from alphafold2_tpu.analysis.metrics_lint import run

    return run(root, files=files)


def _run_dispatch(root, files=None, **_):
    from alphafold2_tpu.analysis.dispatch_lint import run

    return run(root, files=files)


def _run_concurrency(root, files=None, **_):
    from alphafold2_tpu.analysis.concurrency_lint import run

    return run(root, files=files)


# name -> runner(root, files=..., axes=...) -> list[Finding]
PASSES = {
    "compat": _run_compat,
    "trace": _run_trace,
    "sharding": _run_sharding,
    "smoke": _run_smoke,
    "overlap": _run_overlap,
    "schedule": _run_schedule,
    "metrics": _run_metrics,
    "dispatch": _run_dispatch,
    "concurrency": _run_concurrency,
}

# one-line summaries for `--list-passes` (kept here, beside PASSES, so
# adding a pass without a summary fails the pinned listing test)
PASS_SUMMARIES = {
    "compat": "no jax.experimental access / drift-table symbols outside "
              "compat.py",
    "trace": "Python side effects and host-numpy calls inside "
             "jit/pjit/shard_map-reachable code",
    "sharding": "PartitionSpec rank vs annotated rank; unknown or "
                "duplicate mesh axes",
    "smoke": "jax.eval_shape every public op and training preset under "
             "abstract inputs",
    "overlap": "lowered multi-chip programs must interleave collectives "
               "with compute",
    "schedule": "branch-parallel trunks: pair/MSA branches data-"
                "independent before their join",
    "metrics": "every registered metric name documented in "
               "docs/OBSERVABILITY.md and vice versa",
    "dispatch": "registered hot ops have xla_ref arms + parity tests; "
                "no kernel imports outside ops/",
    "concurrency": "lock discipline: multi-entry-point writes without a "
                   "lock, lock-order cycles, blocking calls under a "
                   "lock, daemon threads reaching jax",
}

# passes that verify whole programs rather than the given files: dropped
# from file-scoped invocations unless explicitly selected ("metrics"
# rides here for its docs side: a one-file invocation cannot judge
# whether a documented name is registered ELSEWHERE; "dispatch" still
# runs its AST checks file-scoped, so it stays OUT of this set)
_REPO_WIDE = ("smoke", "overlap", "schedule", "metrics")


def run_passes(root, select=None, files=None, axes=None):
    """Run the selected passes (all by default) over `root`; returns the
    combined finding list, stable-sorted by (path, line, code).

    With an explicit `files` list and no explicit `select`, the
    repo-wide passes (smoke, overlap) are dropped: they trace/lower the
    whole public surface regardless of files, so a "lint this one file"
    invocation would pay the full model-tracing cost and could fail on
    findings unrelated to the requested file. Selecting one explicitly
    (select=... including it) still runs it."""
    if select is None:
        names = [
            n for n in PASSES
            if not (files is not None and n in _REPO_WIDE)
        ]
    else:
        names = list(select)
    findings = []
    for name in names:
        findings.extend(PASSES[name](root, files=files, axes=axes))
    findings.sort(key=lambda f: (str(f.path), f.line, f.code))
    return findings
