"""Pass 3 — sharding annotations.

Checks every `PartitionSpec(...)` / `P(...)` construction (plus
`compat.shard_map` in/out specs) statically:

  SHARD001  spec rank disagrees with the annotated array rank. Annotate
            the line with `# af2lint: rank=N` where N counts the array's
            dimensions; the spec may have FEWER entries (trailing dims
            replicate by JAX convention) but never more.
  SHARD002  axis name not in `parallel/mesh.py` KNOWN_AXES — a typo'd
            axis ("dat", "sq") otherwise survives until a mesh lookup
            KeyErrors mid-trace on real chips. Names bound from an
            `axis_name`-style parameter are invisible to this check (it
            only sees string literals), which is exactly right: those are
            validated against the live mesh at call time.
  SHARD003  the same axis named twice in one spec — JAX rejects this at
            trace time; the static check moves it to CI.
  SHARD004  `shard_map(f, in_specs=(...))` where the literal in_specs
            tuple arity disagrees with f's parameter count (f a lambda or
            a local def) — today this dies deep in shard_map's pytree
            mismatch error; the static message names the actual problem.

Registry checks (PR 10 — the partition-rule registry in
`parallel/rules.py` is DATA, so the lint can validate it without a chip):

  SHARD005  a rule's PartitionSpec names an axis outside KNOWN_AXES.
  SHARD006  a non-scalar leaf of the LIVE model tree (flagship reversible
            train state + e2e state, `eval_shape`d — zero FLOPs) that no
            rule matches, or that a matched rule cannot rank-adapt to —
            the leaf would raise at sharding time on the pod; the lint
            moves that to CI.
  SHARD007  a rule whose regex does not compile.

The registry checks run on whole-repo invocations (like the smoke pass,
they are skipped for file-scoped lint runs). `check_registry` /
`check_coverage` accept fixture rules/trees directly — the test suite
feeds deliberately-broken fixtures through them.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import List, Optional, Sequence, Set

from alphafold2_tpu.analysis.common import (
    Finding,
    dotted_name,
    filter_suppressed,
    iter_py_files,
    parse_file,
    rel,
    suppressed_lines,
)

PASS = "sharding"

_RANK_RE = re.compile(r"#\s*af2lint:\s*rank=(\d+)")

_SPEC_NAMES = {"P", "PartitionSpec"}
_SHARD_MAP_NAMES = {"shard_map", "compat.shard_map", "jax.shard_map"}


def _default_axes(root) -> Optional[Set[str]]:
    """KNOWN_AXES from the live package; falls back to statically parsing
    `<root>/alphafold2_tpu/parallel/mesh.py` (the registry must stay
    checkable even when the package fails to import — that broken state is
    exactly when lint matters). Returns None when neither source yields a
    registry; the caller reports that as its own finding rather than
    silently disabling SHARD002."""
    try:
        from alphafold2_tpu.parallel.mesh import KNOWN_AXES

        return set(KNOWN_AXES)
    except Exception:
        pass
    return _parse_axes_registry(
        Path(root) / "alphafold2_tpu" / "parallel" / "mesh.py"
    )


def _parse_axes_registry(mesh_py: Path) -> Optional[Set[str]]:
    """Static read of `KNOWN_AXES = frozenset({...})` out of mesh.py."""
    try:
        tree = ast.parse(Path(mesh_py).read_text())
    except Exception:
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "KNOWN_AXES"
            for t in node.targets
        ):
            names = {
                c.value
                for c in ast.walk(node.value)
                if isinstance(c, ast.Constant) and isinstance(c.value, str)
            }
            if names:
                return names
    return None


def _spec_axes(call: ast.Call):
    """Flatten a P(...) call's dims: each positional arg is one dim; a
    tuple arg is one dim sharded over several axes. Yields (dim_count,
    [axis string literals])."""
    axes: List[str] = []
    rank = 0
    for a in call.args:
        if isinstance(a, ast.Starred):
            return None  # dynamic — not statically checkable
        rank += 1
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            axes.append(a.value)
        elif isinstance(a, ast.Tuple):
            for el in a.elts:
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    axes.append(el.value)
    return rank, axes


def _rank_annotation(src_lines: List[str], lineno: int) -> Optional[int]:
    m = _RANK_RE.search(src_lines[lineno - 1]) if lineno <= len(src_lines) else None
    return int(m.group(1)) if m else None


def _fn_arity(fn) -> Optional[int]:
    a = fn.args
    if a.vararg or a.kwarg:
        return None
    return len(a.posonlyargs) + len(a.args)


class _Visitor(ast.NodeVisitor):
    def __init__(self, path: str, src: str, axes: Set[str], defs):
        self.path = path
        self.src_lines = src.splitlines()
        self.axes = axes
        self.defs = defs
        self.findings: List[Finding] = []

    def _emit(self, code, line, msg):
        self.findings.append(Finding(PASS, code, self.path, line, msg))

    def visit_Call(self, node: ast.Call):
        name = dotted_name(node.func)
        if name in _SPEC_NAMES:
            self._check_spec(node)
        if name in _SHARD_MAP_NAMES:
            self._check_shard_map(node)
        self.generic_visit(node)

    def _check_spec(self, node: ast.Call):
        flat = _spec_axes(node)
        if flat is None:
            return
        rank, axes = flat
        annotated = _rank_annotation(self.src_lines, node.lineno)
        if annotated is not None and rank > annotated:
            self._emit(
                "SHARD001",
                node.lineno,
                f"PartitionSpec has {rank} entries but the annotated array "
                f"rank is {annotated} (af2lint: rank={annotated}); a spec "
                "longer than the array rank fails at trace time",
            )
        if self.axes:
            for ax in axes:
                if ax not in self.axes:
                    self._emit(
                        "SHARD002",
                        node.lineno,
                        f"mesh axis {ax!r} is not in parallel/mesh.py "
                        f"KNOWN_AXES {sorted(self.axes)} — typo, or a new "
                        "axis missing its registry entry",
                    )
        dup = {a for a in axes if axes.count(a) > 1}
        if dup:
            self._emit(
                "SHARD003",
                node.lineno,
                f"axis {sorted(dup)} appears more than once in one "
                "PartitionSpec — JAX rejects this at trace time",
            )

    def _check_shard_map(self, node: ast.Call):
        fn = None
        if node.args:
            a0 = node.args[0]
            if isinstance(a0, ast.Lambda):
                fn = a0
            elif isinstance(a0, ast.Name) and a0.id in self.defs:
                fn = self.defs[a0.id]
        if fn is None:
            return
        in_specs = next(
            (kw.value for kw in node.keywords if kw.arg == "in_specs"), None
        )
        if not isinstance(in_specs, ast.Tuple):
            return
        arity = _fn_arity(fn)
        if arity is not None and len(in_specs.elts) != arity:
            self._emit(
                "SHARD004",
                node.lineno,
                f"shard_map in_specs has {len(in_specs.elts)} entries but "
                f"the mapped function takes {arity} arguments",
            )


_RULES_SRC = "alphafold2_tpu/parallel/rules.py"


def check_registry(rules=None, axes: Optional[Set[str]] = None) -> List[Finding]:
    """SHARD005/SHARD007 over a rule set (default: the live TP registry):
    every axis named by a rule's spec must be in KNOWN_AXES, and every
    pattern must compile. Takes fixture rules for tests."""
    import re as _re

    if rules is None:
        from alphafold2_tpu.parallel.rules import TP_RULES

        rules = TP_RULES
    if axes is None:
        from alphafold2_tpu.parallel.mesh import KNOWN_AXES

        axes = set(KNOWN_AXES)
    from alphafold2_tpu.parallel.rules import rule_axes

    findings: List[Finding] = []
    for i, (pattern, spec) in enumerate(rules):
        try:
            _re.compile(pattern)
        except _re.error as e:
            findings.append(Finding(
                PASS, "SHARD007", _RULES_SRC, 1,
                f"rule #{i} pattern {pattern!r} is not a valid regex: {e}",
            ))
        for ax in sorted(rule_axes([(pattern, spec)])):
            if ax not in axes:
                findings.append(Finding(
                    PASS, "SHARD005", _RULES_SRC, 1,
                    f"rule #{i} ({pattern!r}) names mesh axis {ax!r} "
                    f"not in KNOWN_AXES {sorted(axes)} — typo, or a "
                    "new axis missing its registry entry",
                ))
    return findings


def check_coverage(rules=None, tree=None) -> List[Finding]:
    """SHARD006: cross-check the registry against a param/state tree —
    by default the LIVE flagship trees (reversible tied-row pretrain
    state AND the full e2e state), obtained chip-free via `eval_shape`.
    Takes a fixture tree for tests."""
    if rules is None:
        from alphafold2_tpu.parallel.rules import TP_RULES

        rules = TP_RULES
    from alphafold2_tpu.parallel.rules import unmatched_leaves

    trees = []
    if tree is not None:
        trees.append(("fixture", tree))
    else:
        try:
            import jax

            from alphafold2_tpu.models import Alphafold2Config, RefinerConfig
            from alphafold2_tpu.training import E2EConfig, e2e_train_state_init
            from alphafold2_tpu.training.harness import (
                TrainConfig,
                train_state_init,
            )

            cfg = Alphafold2Config(
                dim=16, depth=2, heads=2, dim_head=8, max_seq_len=32,
                msa_tie_row_attn=True, cross_attn_compress_ratio=2,
            )
            ecfg = E2EConfig(
                model=Alphafold2Config(
                    dim=16, depth=2, heads=2, dim_head=8, max_seq_len=32,
                    reversible=True, msa_tie_row_attn=True,
                    cross_attn_compress_ratio=2,
                ),
                refiner=RefinerConfig(
                    num_tokens=14, dim=16, depth=1, msg_dim=16
                ),
                mds_iters=2,
            )
            tcfg = TrainConfig(grad_accum=1)
            key = jax.random.PRNGKey(0)
            trees.append(("train_state(flagship)", jax.eval_shape(
                lambda k: train_state_init(k, cfg, tcfg), key)))
            trees.append(("e2e_train_state(reversible)", jax.eval_shape(
                lambda k: e2e_train_state_init(k, ecfg, tcfg), key)))
        except Exception as e:  # the import/trace itself broke
            return [Finding(
                PASS, "SHARD006", _RULES_SRC, 1,
                f"could not eval_shape the live model trees for registry "
                f"coverage: {type(e).__name__}: {e}",
            )]
    findings: List[Finding] = []
    for label, t in trees:
        for name, shape in unmatched_leaves(rules, t):
            findings.append(Finding(
                PASS, "SHARD006", _RULES_SRC, 1,
                f"{label}: no partition rule covers leaf {name!r} "
                f"(shape {shape}) — it would raise at sharding time; add "
                "a rule to parallel/rules.py",
            ))
    return findings


def run(root, files: Optional[Sequence] = None, axes=None) -> List[Finding]:
    axes = set(axes) if axes is not None else _default_axes(root)
    findings: List[Finding] = []
    if axes is None:
        # no registry found anywhere: say so loudly instead of silently
        # running with SHARD002 disabled (an importable-but-broken parallel
        # package is exactly the state the linter exists to catch)
        findings.append(
            Finding(
                PASS,
                "SHARD000",
                "alphafold2_tpu/parallel/mesh.py",
                1,
                "mesh-axis registry unavailable (package import failed and "
                "KNOWN_AXES could not be parsed statically) — SHARD002 "
                "cannot run; fix mesh.py or pass --axes",
            )
        )
        axes = set()
    for path in iter_py_files(root, files):
        src, tree = parse_file(path)
        if tree is None:
            continue
        defs = {
            n.name: n
            for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        v = _Visitor(rel(path, root), src, axes, defs)
        v.visit(tree)
        findings.extend(filter_suppressed(v.findings, suppressed_lines(src)))
    if files is None:
        # whole-repo run: validate the partition-rule registry itself
        # (axes + regexes) and cross-check it against the live model
        # trees chip-free. Skipped for file-scoped invocations, same
        # stance as the smoke pass.
        findings.extend(check_registry(axes=axes or None))
        findings.extend(check_coverage())
    return findings
