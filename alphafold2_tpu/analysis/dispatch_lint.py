"""af2lint pass 8 "dispatch": the kernel-dispatch surface's monopoly.

PR 13 put ONE resolution point (ops/dispatch.py `resolve`) over every
hot op's backend arms. The surface only stays single if drift is a CI
failure, not a review nit — this pass makes four properties static:

  * **DISPATCH001** — every registered op has an ``xla_ref`` arm: the
    run-anywhere reference every kernel arm is pinned against, and the
    arm the cross-backend bench matrix times on chip-free hosts.
  * **DISPATCH002** — every registered op names a chip-free parity test
    that actually exists in tests/test_dispatch.py (kernel arm in
    interpret mode == ``xla_ref``, f32/bf16 + a padded shape). An op
    without parity coverage fails CI, not code review.
  * **DISPATCH003** — no module under ``alphafold2_tpu/`` outside
    ``ops/`` imports a Pallas kernel module
    (``ops/flash_kernel.py`` / ``ops/sparse_kernel.py`` /
    ``ops/quant_kernel.py``) directly: call sites must go through the
    op modules, whose arm choice routes through the registry.
    ``analysis/`` is exempt — the smoke/lowering passes construct
    kernels ON PURPOSE to verify them.
  * **DISPATCH004** — no module under ``alphafold2_tpu/`` outside
    ``ops/knobs.py`` reads an ``AF2_*`` environment variable: one
    validated definition per knob, so the old three-copies-of-tri-state
    drift cannot recur.

Scope for the AST checks: the `alphafold2_tpu` package (tests and
scripts SET env vars for subprocesses, which is fine; they are out of
scope like in the metrics pass). Fixture-injectable via `check_registry`
/ `check_sources` for the linter's own tests.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List, Optional, Sequence

from alphafold2_tpu.analysis.common import (
    Finding,
    dotted_name,
    filter_suppressed,
    iter_py_files,
    parse_file,
    rel,
    suppressed_lines,
)

PASS = "dispatch"
TEST_FILE = Path("tests") / "test_dispatch.py"

_KERNEL_MODULES = ("flash_kernel", "sparse_kernel", "quant_kernel")
_KERNEL_DOTTED = tuple(
    f"alphafold2_tpu.ops.{m}" for m in _KERNEL_MODULES
)


def check_registry(root, registry=None, test_file=None) -> List[Finding]:
    """DISPATCH001/002 over the live registry (or an injected fixture:
    an iterable of objects with .name, .arm_names(), .parity_test)."""
    if registry is None:
        from alphafold2_tpu.ops import dispatch

        registry = [dispatch.get(op) for op in dispatch.ops()]
    test_path = Path(test_file) if test_file else Path(root) / TEST_FILE
    try:
        test_src = test_path.read_text()
    except OSError:
        test_src = None

    findings: List[Finding] = []
    for spec in registry:
        if "xla_ref" not in spec.arm_names():
            findings.append(Finding(
                PASS, "DISPATCH001", "alphafold2_tpu/ops/dispatch.py", 1,
                f"op {spec.name!r} has no xla_ref arm (arms: "
                f"{list(spec.arm_names())}) — every op needs the "
                f"run-anywhere reference arm the parity tier and the "
                f"CPU bench matrix use",
            ))
        if not spec.parity_test:
            findings.append(Finding(
                PASS, "DISPATCH002", "alphafold2_tpu/ops/dispatch.py", 1,
                f"op {spec.name!r} registers no parity test — chip-free "
                f"kernel-vs-xla_ref coverage is mandatory",
            ))
        elif test_src is None:
            findings.append(Finding(
                PASS, "DISPATCH002", str(TEST_FILE), 1,
                f"op {spec.name!r} registers parity test "
                f"{spec.parity_test!r} but {test_path} does not exist",
            ))
        elif f"def {spec.parity_test}(" not in test_src:
            findings.append(Finding(
                PASS, "DISPATCH002", str(TEST_FILE), 1,
                f"op {spec.name!r} registers parity test "
                f"{spec.parity_test!r}, which is not defined in "
                f"{test_path.name}",
            ))
    return findings


def _is_env_read(node) -> bool:
    """A Call reading an AF2_* env var: os.environ.get("AF2_...") /
    os.getenv("AF2_...")."""
    if not (isinstance(node, ast.Call) and node.args):
        return False
    name = dotted_name(node.func)
    if name not in ("os.environ.get", "os.getenv", "environ.get", "getenv"):
        return False
    arg = node.args[0]
    return (isinstance(arg, ast.Constant) and isinstance(arg.value, str)
            and arg.value.startswith("AF2_"))


def _is_env_subscript_read(node) -> bool:
    """os.environ["AF2_..."] in Load context."""
    if not (isinstance(node, ast.Subscript)
            and isinstance(node.ctx, ast.Load)):
        return False
    if dotted_name(node.value) not in ("os.environ", "environ"):
        return False
    sl = node.slice
    # py3.8 wraps the constant in ast.Index
    if isinstance(sl, ast.Index):  # pragma: no cover - py>=3.9 in CI
        sl = sl.value
    return (isinstance(sl, ast.Constant) and isinstance(sl.value, str)
            and sl.value.startswith("AF2_"))


def _kernel_import(node) -> Optional[str]:
    """The kernel module a statement imports, or None."""
    if isinstance(node, ast.Import):
        for alias in node.names:
            if alias.name in _KERNEL_DOTTED:
                return alias.name
    elif isinstance(node, ast.ImportFrom):
        mod = node.module or ""
        if mod in _KERNEL_DOTTED:
            return mod
        if mod == "alphafold2_tpu.ops":
            for alias in node.names:
                if alias.name in _KERNEL_MODULES:
                    return f"{mod}.{alias.name}"
    return None


def check_sources(root, files: Optional[Sequence] = None) -> List[Finding]:
    """DISPATCH003/004 over the package sources."""
    root = Path(root)
    pkg = root / "alphafold2_tpu"
    findings: List[Finding] = []
    for path in iter_py_files(root, files):
        p = Path(path)
        if "tests" in p.parts:
            continue
        try:
            inside = p.resolve().is_relative_to(pkg.resolve())
        except AttributeError:  # py<3.9 has no is_relative_to
            inside = str(pkg) in str(p.resolve())
        if not inside:
            continue
        parts = p.parts
        in_ops = "ops" in parts
        in_analysis = "analysis" in parts
        is_knobs = p.name == "knobs.py" and in_ops
        src, tree = parse_file(p)
        if tree is None:
            continue
        supp = suppressed_lines(src)
        file_findings: List[Finding] = []
        for node in ast.walk(tree):
            if not (in_ops or in_analysis):
                mod = _kernel_import(node) if isinstance(
                    node, (ast.Import, ast.ImportFrom)) else None
                if mod:
                    file_findings.append(Finding(
                        PASS, "DISPATCH003", rel(p, root), node.lineno,
                        f"direct kernel import {mod!r} outside ops/ — "
                        f"route through the op module so the arm choice "
                        f"goes through ops/dispatch.py resolve()",
                    ))
            if not is_knobs and (
                _is_env_read(node) or _is_env_subscript_read(node)
            ):
                file_findings.append(Finding(
                    PASS, "DISPATCH004", rel(p, root), node.lineno,
                    "AF2_* env var read outside ops/knobs.py — every "
                    "knob has exactly one validated definition there",
                ))
        findings.extend(filter_suppressed(file_findings, supp))
    return findings


def run(root, files: Optional[Sequence] = None, registry=None,
        test_file=None) -> List[Finding]:
    findings = check_sources(root, files=files)
    # the registry side is repo-level (it inspects the live registry and
    # the test file, not the given sources); skip it for file-scoped
    # invocations, like the metrics pass's docs direction
    if files is None:
        findings.extend(check_registry(root, registry=registry,
                                       test_file=test_file))
    return findings
