"""Shared plumbing for the af2lint passes: the Finding record, repo file
iteration, and `# af2lint: disable=CODE` suppression comments."""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Iterable, List, Optional, Sequence

# directories never worth analyzing (caches, VCS, build output,
# third-party code inside the tree — an in-repo virtualenv would otherwise
# flood the strict gate with findings from JAX's own source)
_SKIP_DIRS = {
    ".git",
    ".pytest_jax_cache",
    "__pycache__",
    ".eggs",
    "build",
    "dist",
    "node_modules",
    ".venv",
    "venv",
    ".tox",
    ".nox",
    "site-packages",
}

_SUPPRESS_RE = re.compile(r"#\s*af2lint:\s*disable=([A-Z0-9,\s]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One analyzer finding. All findings are failures under --strict."""

    pass_name: str
    code: str
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} [{self.pass_name}] {self.message}"


def iter_py_files(root, files: Optional[Sequence] = None) -> List[Path]:
    """The .py files to analyze: an explicit list, or everything under
    `root` minus skip-dirs."""
    if files is not None:
        return [Path(f) for f in files]
    root = Path(root)
    out = []
    for p in sorted(root.rglob("*.py")):
        if any(part in _SKIP_DIRS for part in p.parts):
            continue
        out.append(p)
    return out


def parse_file(path: Path):
    """(source, ast.Module) for `path`; returns (source, None) on syntax
    errors — passes report those as their own finding rather than crash."""
    src = Path(path).read_text()
    try:
        return src, ast.parse(src, filename=str(path))
    except SyntaxError:
        return src, None


def suppressed_lines(src: str) -> dict:
    """{line_number: set(codes)} for `# af2lint: disable=...` comments."""
    out = {}
    for i, line in enumerate(src.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if m:
            out[i] = {c.strip() for c in m.group(1).split(",") if c.strip()}
    return out


def suppressed(finding: Finding, supp: dict) -> bool:
    return finding.code in supp.get(finding.line, ())


def filter_suppressed(findings: Iterable[Finding], supp: dict) -> List[Finding]:
    return [f for f in findings if not suppressed(f, supp)]


def rel(path, root) -> str:
    """Repo-relative path when possible (stable CI output)."""
    try:
        return str(Path(path).resolve().relative_to(Path(root).resolve()))
    except ValueError:
        return str(path)


def dotted_name(node) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None
