"""af2lint pass 7 "metrics": metric names vs docs/OBSERVABILITY.md.

The operations plane made metric names an API: dashboards scrape them,
the SLO engine selects on them, and the runbook (docs/OPERATIONS.md)
keys diagnostics off them. Nothing enforced the contract — rename a
counter and every consumer silently reads zero. This pass makes the
drift static:

  * every metric name registered with a STRING LITERAL at a
    `.counter(` / `.gauge(` / `.histogram(` call site in
    `alphafold2_tpu/` must appear in the metric inventory block of
    docs/OBSERVABILITY.md (METRICS001);
  * every name in the inventory must be registered somewhere
    (METRICS002) — a deleted metric must leave the docs with it;
  * the inventory block itself must exist, fenced by
    ``<!-- af2lint:metrics:begin -->`` / ``<!-- af2lint:metrics:end -->``
    markers (METRICS003).

Dynamic names (f-strings like `CompileTracker`'s ``f"{prefix}_total"``)
cannot be resolved statically; they become suffix WILDCARDS
(``*_total``) that vouch for matching inventory entries — so
`serving_compile_last_seconds` is documentable even though no literal
registers it — but are exempt from METRICS001 themselves. A wildcard
whose literal part is too short to be distinctive (``*_total`` would
match MOST counters, making METRICS002 vacuous) vouches only for names
it forms with a literal ``prefix="..."`` kwarg collected from the same
scope — the `CompileTracker(prefix="serving_compile")` idiom.

Scope: the `alphafold2_tpu` package minus `analysis/` (the linter's own
smoke fixtures register throwaway names) and minus tests. Suppress a
deliberate internal-only metric with ``# af2lint: disable=METRICS001``.
"""

from __future__ import annotations

import ast
import fnmatch
import re
from pathlib import Path
from typing import List, Optional, Sequence, Tuple

from alphafold2_tpu.analysis.common import (
    Finding,
    filter_suppressed,
    iter_py_files,
    parse_file,
    rel,
    suppressed_lines,
)

PASS = "metrics"
DOC_PATH = Path("docs") / "OBSERVABILITY.md"
BEGIN_MARK = "<!-- af2lint:metrics:begin -->"
END_MARK = "<!-- af2lint:metrics:end -->"

_REGISTRY_METHODS = ("counter", "gauge", "histogram")
#: a metric row: the FIRST backticked token of a table line — later
#: cells backtick label names, which are not metric names
_DOC_NAME_RE = re.compile(
    r"^\|\s*`([a-zA-Z_:][a-zA-Z0-9_:]*)`", re.MULTILINE
)
_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def doc_inventory(root) -> Tuple[Optional[set], int]:
    """(documented names, marker line) from the OBSERVABILITY.md
    inventory block; (None, 0) when the markers are missing."""
    path = Path(root) / DOC_PATH
    try:
        text = path.read_text()
    except OSError:
        return None, 0
    begin = text.find(BEGIN_MARK)
    end = text.find(END_MARK)
    if begin < 0 or end < 0 or end < begin:
        return None, 0
    line = text[:begin].count("\n") + 1
    block = text[begin:end]
    names = {
        m for m in _DOC_NAME_RE.findall(block) if _NAME_RE.match(m)
    }
    return names, line


def _literal_or_pattern(node) -> Tuple[Optional[str], Optional[str]]:
    """(literal_name, wildcard_pattern) for a metric-name argument node:
    a Constant str is a literal; a JoinedStr maps each interpolation to
    `*`; anything else is unresolvable (None, None)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value, None
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:
                parts.append("*")
        return None, "".join(parts)
    return None, None


#: a wildcard's non-`*` part must be at least this long to vouch on its
#: own — `*_total` (6 literal chars) matches most counters and would
#: make the stale-docs direction vacuous; `*_last_seconds` (13) is a
#: distinctive dynamic family
_MIN_DISTINCTIVE_LITERAL = 8


def collect_call_sites(root, files=None):
    """(literals, patterns, prefixes): metric names registered in the
    package. literals = [(name, path, line, suppressed)]; patterns =
    [wildcard, ...]; prefixes = literal `prefix="..."` kwarg values (the
    dynamic-name-factory idiom, used to anchor short wildcards)."""
    root = Path(root)
    pkg = root / "alphafold2_tpu"
    literals, patterns, prefixes = [], [], set()
    for path in iter_py_files(root, files):
        p = Path(path)
        parts = p.parts
        if "tests" in parts:
            continue
        try:
            inside = p.resolve().is_relative_to(pkg.resolve())
        except AttributeError:  # py<3.9 has no is_relative_to
            inside = str(pkg) in str(p.resolve())
        if not inside or "analysis" in parts:
            continue
        src, tree = parse_file(p)
        if tree is None:
            continue
        supp = suppressed_lines(src)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            for kw in node.keywords:
                if (kw.arg == "prefix"
                        and isinstance(kw.value, ast.Constant)
                        and isinstance(kw.value.value, str)):
                    prefixes.add(kw.value.value)
            if not (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _REGISTRY_METHODS
                    and node.args):
                continue
            name, pattern = _literal_or_pattern(node.args[0])
            if name is not None:
                literals.append((name, p, node.lineno, supp))
            elif pattern is not None and "*" in pattern:
                patterns.append(pattern)
    return literals, patterns, prefixes


def _vouched(name: str, patterns, prefixes) -> bool:
    """Whether a documented-but-not-literally-registered name is covered
    by a dynamic call site: distinctive wildcards match directly; short
    wildcards only through a collected `prefix=` literal."""
    for pat in patterns:
        literal = pat.replace("*", "")
        if len(literal) >= _MIN_DISTINCTIVE_LITERAL:
            if fnmatch.fnmatch(name, pat):
                return True
        elif any(fnmatch.fnmatch(name, pat.replace("*", p, 1))
                 for p in prefixes):
            return True
    return False


def run(root, files: Optional[Sequence] = None) -> List[Finding]:
    documented, doc_line = doc_inventory(root)
    if documented is None:
        return [Finding(
            PASS, "METRICS003", str(DOC_PATH), 1,
            f"metric inventory block not found: expected {BEGIN_MARK!r} "
            f"... {END_MARK!r} markers in docs/OBSERVABILITY.md",
        )]
    literals, patterns, prefixes = collect_call_sites(root, files)
    findings: List[Finding] = []
    seen = set()
    for name, path, line, supp in literals:
        seen.add(name)
        if name not in documented:
            findings.extend(filter_suppressed([Finding(
                PASS, "METRICS001", rel(path, root), line,
                f"metric {name!r} is registered here but missing from the "
                f"docs/OBSERVABILITY.md inventory — document it (or "
                f"suppress an internal-only metric)",
            )], supp))
    # files-scoped invocations see only a slice of the call sites; the
    # documented-but-unused direction is only meaningful repo-wide
    if files is None:
        for name in sorted(documented - seen):
            if _vouched(name, patterns, prefixes):
                continue  # vouched for by a dynamic-prefix call site
            findings.append(Finding(
                PASS, "METRICS002", str(DOC_PATH), doc_line,
                f"documented metric {name!r} is never registered by any "
                f"counter()/gauge()/histogram() call site — stale docs "
                f"or a renamed metric",
            ))
    return findings
