"""CLI: ``python -m alphafold2_tpu.analysis [--strict] [--select ...]``.

Exit status: 0 when clean (always, without --strict); with --strict, 1
when any finding survives. CI runs ``--strict`` as a build gate and
``--select smoke`` as the fast pre-test gate (.github/workflows/test.yml).

``--json`` emits one machine-readable object — findings grouped per
pass with rule ids and locations — for tooling that wants structure
rather than the flat ``--format json`` list. ``--list-passes`` prints
the registered passes with their one-line summaries and exits.
"""

from __future__ import annotations

import argparse
import json
import sys

from alphafold2_tpu.analysis import PASSES, PASS_SUMMARIES, run_passes


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m alphafold2_tpu.analysis",
        description="af2lint: JAX-aware static analysis "
        "(compat / trace / sharding / smoke / overlap / schedule / "
        "metrics / dispatch / concurrency)",
    )
    ap.add_argument(
        "paths",
        nargs="*",
        help="files to analyze (default: the whole tree under --root). "
        "With explicit paths the repo-wide smoke pass is skipped unless "
        "selected via --select",
    )
    ap.add_argument(
        "--root",
        default=".",
        help="repo root for file discovery and relative paths (default: cwd)",
    )
    ap.add_argument(
        "--select",
        default=None,
        help=f"comma-separated pass names (default: all of {','.join(PASSES)})",
    )
    ap.add_argument(
        "--strict",
        action="store_true",
        help="exit 1 on any finding (CI gate mode)",
    )
    ap.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        help="output format",
    )
    ap.add_argument(
        "--axes",
        default=None,
        help="comma-separated mesh-axis allowlist for the sharding pass "
        "(default: parallel/mesh.py KNOWN_AXES)",
    )
    ap.add_argument(
        "--json",
        action="store_true",
        help="emit one machine-readable JSON object (findings grouped "
        "per pass, with rule ids and locations); implies no text output",
    )
    ap.add_argument(
        "--list-passes",
        action="store_true",
        help="list the registered passes with their summaries and exit",
    )
    args = ap.parse_args(argv)

    if args.list_passes:
        for name in PASSES:
            print(f"{name:<12} {PASS_SUMMARIES.get(name, '')}")
        print(f"{len(PASSES)} passes")
        return 0

    select = None
    if args.select:
        select = [s.strip() for s in args.select.split(",") if s.strip()]
        unknown = [s for s in select if s not in PASSES]
        if unknown:
            ap.error(f"unknown pass(es) {unknown}; available: {list(PASSES)}")
    axes = (
        {a.strip() for a in args.axes.split(",") if a.strip()}
        if args.axes
        else None
    )
    files = args.paths or None

    findings = run_passes(args.root, select=select, files=files, axes=axes)

    if args.json:
        names = select or list(PASSES)
        by_pass = {n: [] for n in names}
        for f in findings:
            by_pass.setdefault(f.pass_name, []).append({
                "rule": f.code,
                "path": f.path,
                "line": f.line,
                "message": f.message,
            })
        print(json.dumps({
            "passes": names,
            "findings": by_pass,
            "total": len(findings),
            "strict": bool(args.strict),
        }, indent=2, sort_keys=True))
    elif args.format == "json":
        print(
            json.dumps(
                [f.__dict__ for f in findings], indent=2, sort_keys=True
            )
        )
    else:
        for f in findings:
            print(f.render())
        names = select or list(PASSES)
        print(
            f"af2lint: {len(findings)} finding(s) from passes "
            f"[{', '.join(names)}]"
        )
    return 1 if (args.strict and findings) else 0


if __name__ == "__main__":
    sys.exit(main())
