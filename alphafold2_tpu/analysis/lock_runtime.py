"""Runtime validation arm for the concurrency pass: instrumented locks.

`concurrency_lint` builds the lock-acquisition graph STATICALLY and
honestly documents its blind spot: callables stored in containers
(health-probe registries, tick-hook lists, done-callbacks) are dynamic
call edges it cannot resolve. This module closes that gap the way
overlap-lint's runtime assertions close its: wrap the real locks of a
live system, record the ACTUAL acquisition-order graph plus
held-while-blocking events while the existing chaos acceptance tests
drive real traffic, and assert the observed graph is acyclic.

Usage (see tests/test_chaos.py)::

    mon = LockMonitor()
    mon.instrument(fleet)            # wraps every Lock/RLock attr
    mon.instrument(fleet._health)
    ... drive the chaos scenario ...
    mon.assert_acyclic()             # observed lock-order graph
    snap = mon.snapshot()            # edges, counts, long holds

Instrumentation swaps a ``self._lock`` attribute for a proxy that
delegates ``acquire``/``release`` to the SAME underlying lock, so
mutual exclusion is untouched even for threads already running (Python
re-reads the attribute at each ``with self._lock:``) and for
``threading.Condition`` objects built over the raw lock. Bookkeeping
is per-thread (a thread-local held-stack) plus one leaf-only registry
lock, so the monitor itself cannot introduce an ordering edge.

A hold longer than ``long_hold_s`` is recorded as a held-while-blocking
event (name, duration, holder thread). Condition waits release the raw
lock without telling the proxy, so long-hold events are diagnostic
only — the acyclicity assertion is the contract.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

_LOCK_TYPES = (type(threading.Lock()), type(threading.RLock()))


class _InstrumentedLock:
    """Delegating proxy over a real Lock/RLock with order bookkeeping."""

    def __init__(self, raw, name: str, monitor: "LockMonitor"):
        self._raw = raw
        self._name = name
        self._mon = monitor

    # the two methods Condition and `with` need
    def acquire(self, blocking=True, timeout=-1):
        got = self._raw.acquire(blocking, timeout)
        if got:
            self._mon._on_acquire(self._name)
        return got

    def release(self):
        self._mon._on_release(self._name)
        self._raw.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._raw.locked()

    def __repr__(self):
        return f"<instrumented {self._name} over {self._raw!r}>"


class LockMonitor:
    """Records the live lock-acquisition-order graph across threads."""

    def __init__(self, long_hold_s: float = 0.05):
        self.long_hold_s = long_hold_s
        self._tls = threading.local()
        self._reg = threading.Lock()   # leaf-only: never held while
        #                                acquiring an instrumented lock
        self._edges: Dict[Tuple[str, str], int] = {}
        self._acquires: Dict[str, int] = {}
        self._long_holds: List[dict] = []

    # ------------------------------------------------------ bookkeeping

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _on_acquire(self, name: str):
        st = self._stack()
        with self._reg:
            self._acquires[name] = self._acquires.get(name, 0) + 1
            for held, _t0 in st:
                if held != name:
                    key = (held, name)
                    self._edges[key] = self._edges.get(key, 0) + 1
        st.append((name, time.monotonic()))

    def _on_release(self, name: str):
        st = self._stack()
        # release order may not mirror acquire order — pop the newest
        # matching entry
        for i in range(len(st) - 1, -1, -1):
            if st[i][0] == name:
                _n, t0 = st.pop(i)
                held_for = time.monotonic() - t0
                if held_for >= self.long_hold_s:
                    with self._reg:
                        self._long_holds.append({
                            "lock": name,
                            "held_s": round(held_for, 4),
                            "thread": threading.current_thread().name,
                        })
                return

    # ------------------------------------------------------ wrapping

    def wrap(self, raw, name: str) -> _InstrumentedLock:
        if isinstance(raw, _InstrumentedLock):
            return raw
        return _InstrumentedLock(raw, name, self)

    def instrument(self, obj, label: Optional[str] = None) -> List[str]:
        """Swap every plain Lock/RLock attribute of `obj` for an
        instrumented proxy named `<TypeName>.<attr>`. Returns the names
        wrapped. Safe on live objects: the proxy delegates to the same
        raw lock, so mutual exclusion is unchanged."""
        label = label or type(obj).__name__
        wrapped = []
        for attr, val in sorted(vars(obj).items()):
            if isinstance(val, _LOCK_TYPES):
                name = f"{label}.{attr}"
                setattr(obj, attr, self.wrap(val, name))
                wrapped.append(name)
        return wrapped

    # ------------------------------------------------------ the verdicts

    def edges(self) -> Dict[Tuple[str, str], int]:
        with self._reg:
            return dict(self._edges)

    def cycles(self) -> List[List[str]]:
        """Every distinct cycle in the observed acquisition-order graph
        (a self-edge never arises: re-entry on a plain Lock deadlocks
        before it could be recorded, and RLock re-entry is filtered at
        edge time by the held != name guard)."""
        adj: Dict[str, set] = {}
        for (a, b) in self.edges():
            adj.setdefault(a, set()).add(b)
        out, seen = [], set()
        WHITE, GREY, BLACK = 0, 1, 2
        color: Dict[str, int] = {}

        def dfs(node, path):
            color[node] = GREY
            for nxt in sorted(adj.get(node, ())):
                if color.get(nxt, WHITE) == GREY:
                    cyc = path[path.index(nxt):] + [nxt]
                    key = frozenset(cyc)
                    if key not in seen:
                        seen.add(key)
                        out.append(cyc)
                elif color.get(nxt, WHITE) == WHITE:
                    dfs(nxt, path + [nxt])
            color[node] = BLACK

        for n in sorted(adj):
            if color.get(n, WHITE) == WHITE:
                dfs(n, [n])
        return out

    def assert_acyclic(self):
        cycles = self.cycles()
        if cycles:
            rendered = "; ".join(" -> ".join(c) for c in cycles)
            raise AssertionError(
                f"observed lock-order graph has cycle(s): {rendered} — "
                f"two threads taking these locks in opposite orders can "
                f"deadlock (see docs/STATIC_ANALYSIS.md pass 9)")

    def snapshot(self) -> dict:
        with self._reg:
            return {
                "acquires": dict(self._acquires),
                "edges": [
                    {"held": a, "acquired": b, "count": n}
                    for (a, b), n in sorted(self._edges.items())
                ],
                "long_holds": list(self._long_holds),
            }
