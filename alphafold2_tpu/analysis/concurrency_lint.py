"""af2lint pass 9 — "concurrency": lock discipline and thread-interaction
lint over the serving/telemetry/reliability packages.

The fleet is a genuinely concurrent system (dispatcher, health monitor,
autoscaler, ops ticker, featurize pool, watchdog, HTTP handlers share
~30 locks across 15 modules) and every concurrency bug so far was found
LIVE: the probe/record_failure drain race, the kill-vs-scale-down
double-drain, the SIGTERM self-deadlock, the daemon-thread-in-jax
teardown segfault. This pass encodes those bug classes statically:

  * **CONC001** — a ``self._*`` attribute mutated from >= 2 distinct
    thread entry points without a common lock scope. Entry points are
    DISCOVERED, not hand-listed: ``threading.Thread(target=...)``
    targets, ``add_tick(...)`` ticker hooks, ``do_*`` methods on
    ``BaseHTTPRequestHandler`` subclasses, ``add_done_callback(...)``
    callbacks, ``signal.signal(...)`` handlers — plus one implicit
    "api" root modeling the caller thread of any class that owns an
    entry point. Lock scope = enclosing ``with self._lock:`` regions.
  * **CONC002** — lock-order inversion: the cross-module
    lock-acquisition graph (which locks are acquired while which are
    held, including through resolvable call edges) must be acyclic.
    A self-edge on a plain ``Lock`` (re-acquisition while held) is a
    length-1 cycle; RLocks are exempt from self-edges.
  * **CONC003** — a known-blocking call (engine build via a
    ``*factory`` call, ``_executable_for`` / ``.lower().compile()``
    XLA compiles, ``Thread.join``, unbounded ``Queue.get``,
    ``.stats()`` snapshots) made while holding any analyzed lock —
    the PR 15 SIGTERM self-deadlock class.
  * **CONC004** — a ``daemon=True`` thread whose target's call graph
    can reach jax — the teardown-segfault class (the interpreter kills
    daemon threads mid-device-call at exit).
  * **CONC000** — allowlist hygiene: an entry without a written
    justification, or one that matches nothing (stale).

Intentional patterns are allowlisted in ``concurrency_allowlist.json``
(same directory); every entry carries a mandatory ``why`` string.
Findings can also be suppressed per-line with
``# af2lint: disable=CONC00x``.

Honest limits (documented, by design): lock regions are ``with``-based
only (bare ``.acquire()``/``.release()`` pairs are not modeled); call
edges resolve ``self._m()``, ``self._attr.m()`` where ``self._attr``
was built from a class in the analyzed set, module functions, and
nested ``def``s — callables stored in containers (health-monitor probe
registries, tick hook lists) are dynamic and out of reach, which is
exactly why `analysis/lock_runtime.py` validates the same graph against
live chaos executions.

Fixture-injectable like the other passes: ``run(root, files=[...])``
analyzes exactly that file set as its universe.
"""

from __future__ import annotations

import ast
import json
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from alphafold2_tpu.analysis.common import (
    Finding,
    dotted_name,
    filter_suppressed,
    parse_file,
    rel,
    suppressed_lines,
)

PASS = "concurrency"
_SCOPE_PKGS = ("serving", "telemetry", "reliability")
ALLOWLIST_PATH = Path(__file__).with_name("concurrency_allowlist.json")

_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_QUEUE_CTORS = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue"}


# --------------------------------------------------------------- model


class _Meth:
    """One function body: writes, lock acquisitions, calls, blocking ops."""

    def __init__(self, owner, name: str, line: int):
        self.owner = owner              # _Cls or _Mod
        self.name = name                # may be dotted for nested defs
        self.line = line
        self.writes: List[Tuple[str, int, frozenset]] = []
        self.acquires: List[Tuple[str, int, frozenset]] = []
        self.calls: List[Tuple[tuple, int, frozenset]] = []
        self.blocking: List[Tuple[str, int, frozenset]] = []
        self.jax_local = False          # body references a jax alias

    @property
    def qualname(self) -> str:
        if isinstance(self.owner, _Cls):
            return f"{self.owner.name}.{self.name}"
        return f"{self.owner.stem}.{self.name}"

    @property
    def mod(self) -> "_Mod":
        return self.owner.mod if isinstance(self.owner, _Cls) else self.owner


class _Cls:
    def __init__(self, mod: "_Mod", name: str, line: int):
        self.mod = mod
        self.name = name
        self.line = line
        self.locks: Dict[str, str] = {}   # attr -> ctor kind (Lock/RLock/..)
        self.threads: set = set()         # attrs assigned threading.Thread
        self.queues: set = set()          # attrs assigned queue.Queue
        self.collab: Dict[str, str] = {}  # attr -> class name
        self.meths: Dict[str, _Meth] = {}
        self.http_handler = False


class _Mod:
    def __init__(self, path: str):
        self.path = path
        self.stem = Path(path).stem
        self.classes: Dict[str, _Cls] = {}
        self.funcs: Dict[str, _Meth] = {}
        self.entries: List[tuple] = []       # (kind, owner, caller, desc, line)
        self.spawns: List[tuple] = []        # (meth, line, daemon, name, descs)
        self.jax_aliases: set = set()
        self.mod_locks: Dict[str, str] = {}  # name -> ctor kind
        self.supp: dict = {}


def _is_ctor(node, kinds) -> Optional[str]:
    """The ctor kind if `node` is a call to threading.Lock()-like."""
    if not isinstance(node, ast.Call):
        return None
    d = dotted_name(node.func)
    if d is None:
        return None
    last = d.rsplit(".", 1)[-1]
    return last if last in kinds else None


def _is_thread_ctor(node) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = dotted_name(node.func)
    return d is not None and d.rsplit(".", 1)[-1] == "Thread"


def _callable_descs(node) -> List[tuple]:
    """Call-target descriptors for a callback argument: a bound method,
    a bare name, or the calls inside a lambda body."""
    if isinstance(node, ast.Lambda):
        out = []
        for sub in ast.walk(node.body):
            if isinstance(sub, ast.Call):
                d = _call_desc(sub.func)
                if d is not None:
                    out.append(d)
        return out
    d = _call_desc(node)
    return [d] if d is not None else []


def _call_desc(func) -> Optional[tuple]:
    """("self", m) | ("attr", a, m) | ("name", f) | ("ext", dotted)."""
    if isinstance(func, ast.Attribute):
        base = func.value
        if isinstance(base, ast.Name) and base.id == "self":
            return ("self", func.attr)
        if (isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"):
            return ("attr", base.attr, func.attr)
    if isinstance(func, ast.Name):
        return ("name", func.id)
    d = dotted_name(func)
    return ("ext", d) if d else None


# ------------------------------------------------------------ collection


class _FnWalker(ast.NodeVisitor):
    """Walk one function body tracking the `with self._lock:` stack."""

    def __init__(self, meth: _Meth, cls: Optional[_Cls], mod: _Mod,
                 in_init: bool):
        self.meth, self.cls, self.mod = meth, cls, mod
        self.in_init = in_init
        self.held: List[str] = []
        self.local_threads: set = set()
        self.local_queues: set = set()

    # ---- lock identity

    def _lock_id(self, expr) -> Optional[str]:
        d = dotted_name(expr)
        if d is None:
            return None
        if d.startswith("self.") and d.count(".") == 1:
            attr = d.split(".", 1)[1]
            if self.cls is not None and attr in self.cls.locks:
                return f"{self.cls.name}.{attr}"
        elif "." not in d and d in self.mod.mod_locks:
            return f"{self.mod.stem}.{d}"
        return None

    def _lock_kind(self, lock_id: str) -> str:
        owner, attr = lock_id.split(".", 1)
        if self.cls is not None and owner == self.cls.name:
            return self.cls.locks.get(attr, "Lock")
        return self.mod.mod_locks.get(attr, "Lock")

    # ---- with-regions

    def visit_With(self, node):
        acquired = []
        for item in node.items:
            lid = self._lock_id(item.context_expr)
            if lid is not None:
                self.meth.acquires.append(
                    (lid, item.context_expr.lineno, frozenset(self.held)))
                self.held.append(lid)
                acquired.append(lid)
            else:
                # still visit the context expr (calls inside it)
                self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.held.pop()

    visit_AsyncWith = visit_With

    # ---- writes

    def _record_write(self, attr: str, line: int):
        if self.in_init or not attr.startswith("_"):
            return
        if self.cls is not None and attr in self.cls.locks:
            return
        self.meth.writes.append((attr, line, frozenset(self.held)))

    def _classify_self_assign(self, attr: str, value):
        kind = _is_ctor(value, _LOCK_CTORS)
        if kind is not None and self.cls is not None:
            self.cls.locks[attr] = kind
            return
        if _is_thread_ctor(value) and self.cls is not None:
            self.cls.threads.add(attr)
            return
        if _is_ctor(value, _QUEUE_CTORS) is not None and self.cls is not None:
            self.cls.queues.add(attr)
            return
        if self.in_init and self.cls is not None and isinstance(value, ast.Call):
            d = dotted_name(value.func)
            if d is not None:
                last = d.rsplit(".", 1)[-1]
                if last[:1].isupper():
                    self.cls.collab[attr] = last

    def visit_Assign(self, node):
        for tgt in node.targets:
            self._handle_target(tgt, node.value, node.lineno)
        self.visit(node.value)

    def _handle_target(self, tgt, value, line):
        if isinstance(tgt, ast.Attribute) and isinstance(tgt.value, ast.Name) \
                and tgt.value.id == "self":
            self._classify_self_assign(tgt.attr, value)
            self._record_write(tgt.attr, line)
        elif isinstance(tgt, ast.Subscript):
            d = dotted_name(tgt.value)
            if d and d.startswith("self.") and d.count(".") == 1:
                self._record_write(d.split(".", 1)[1], line)
        elif isinstance(tgt, ast.Name) and value is not None:
            if _is_thread_ctor(value):
                self.local_threads.add(tgt.id)
            elif _is_ctor(value, _QUEUE_CTORS) is not None:
                self.local_queues.add(tgt.id)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._handle_target(el, None, line)

    def visit_AugAssign(self, node):
        self._handle_target(node.target, None, node.lineno)
        self.visit(node.value)

    def visit_Delete(self, node):
        for tgt in node.targets:
            if isinstance(tgt, ast.Attribute) and \
                    isinstance(tgt.value, ast.Name) and tgt.value.id == "self":
                self._record_write(tgt.attr, node.lineno)
            elif isinstance(tgt, ast.Subscript):
                d = dotted_name(tgt.value)
                if d and d.startswith("self.") and d.count(".") == 1:
                    self._record_write(d.split(".", 1)[1], node.lineno)

    # ---- calls

    def visit_Call(self, node):
        kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}
        if _is_thread_ctor(node):
            daemon = isinstance(kwargs.get("daemon"), ast.Constant) \
                and kwargs["daemon"].value is True
            name = None
            nk = kwargs.get("name")
            if isinstance(nk, ast.Constant):
                name = nk.value
            elif isinstance(nk, ast.JoinedStr):
                name = "".join(
                    v.value for v in nk.values
                    if isinstance(v, ast.Constant)) + "*"
            descs = _callable_descs(kwargs["target"]) \
                if "target" in kwargs else []
            self.mod.spawns.append(
                (self.meth, node.lineno, daemon, name, tuple(descs)))
            for d in descs:
                self.mod.entries.append(
                    ("thread", self.cls, self.meth, d, node.lineno))
        func = node.func
        if isinstance(func, ast.Attribute) and \
                func.attr in ("add_tick", "add_done_callback") and node.args:
            kind = "tick" if func.attr == "add_tick" else "done_callback"
            for d in _callable_descs(node.args[0]):
                self.mod.entries.append(
                    (kind, self.cls, self.meth, d, node.lineno))
        d_full = dotted_name(func)
        if d_full == "signal.signal" and len(node.args) >= 2:
            for d in _callable_descs(node.args[1]):
                self.mod.entries.append(
                    ("signal", self.cls, self.meth, d, node.lineno))
        desc = _call_desc(func)
        if desc is not None:
            self.meth.calls.append((desc, node.lineno, frozenset(self.held)))
        self._check_blocking(node, func, kwargs)
        self.generic_visit(node)

    def _check_blocking(self, node, func, kwargs):
        what = None
        if isinstance(func, ast.Attribute):
            recv = dotted_name(func.value)
            if func.attr == "join":
                is_thread = (
                    (recv and recv.startswith("self.")
                     and self.cls is not None
                     and recv.split(".", 1)[1] in self.cls.threads)
                    or (recv in self.local_threads)
                )
                if is_thread:
                    what = "Thread.join"
            elif func.attr == "get":
                is_queue = (
                    (recv and recv.startswith("self.")
                     and self.cls is not None
                     and recv.split(".", 1)[1] in self.cls.queues)
                    or (recv in self.local_queues)
                )
                if is_queue and "timeout" not in kwargs:
                    what = "unbounded Queue.get"
            elif func.attr == "stats":
                what = "stats() snapshot"
            elif func.attr == "compile" and isinstance(func.value, ast.Call):
                inner = func.value.func
                inner_name = inner.attr if isinstance(inner, ast.Attribute) \
                    else (dotted_name(inner) or "").rsplit(".", 1)[-1]
                if inner_name in ("lower", "jit"):
                    what = "XLA compile (.lower().compile())"
        callee = None
        if isinstance(func, ast.Attribute):
            callee = func.attr
        elif isinstance(func, ast.Name):
            callee = func.id
        if callee is not None and what is None:
            if callee == "_executable_for":
                what = "compile (_executable_for)"
            elif (callee == "factory"
                  or (callee.endswith("_factory")
                      and not callee.lstrip("_").startswith("make"))):
                what = "engine build (factory call)"
        if what is not None:
            self.meth.blocking.append(
                (what, node.lineno, frozenset(self.held)))

    # ---- jax references

    def visit_Name(self, node):
        if node.id in self.mod.jax_aliases:
            self.meth.jax_local = True

    # ---- nested defs: separate bodies, fresh lock stack

    def visit_FunctionDef(self, node):
        _collect_function(node, self.cls, self.mod,
                          prefix=self.meth.name, in_init=False)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        # lambda bodies execute later, not under the current lock stack
        pass


def _collect_function(node, cls: Optional[_Cls], mod: _Mod,
                      prefix: Optional[str] = None, in_init: bool = False):
    name = f"{prefix}.{node.name}" if prefix else node.name
    meth = _Meth(cls if cls is not None else mod, name, node.lineno)
    if cls is not None:
        cls.meths[name] = meth
    else:
        mod.funcs[name] = meth
    w = _FnWalker(meth, cls, mod, in_init=in_init)
    for stmt in node.body:
        w.visit(stmt)
    return meth


def _prescan_class(stmt: ast.ClassDef, cls: _Cls):
    """Classify `self.X = <ctor>` attributes BEFORE walking bodies, so a
    method defined above __init__ still resolves `with self._lock:`."""
    for sub in stmt.body:
        if not isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        in_init = sub.name == "__init__"
        for node in ast.walk(sub):
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if not (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    continue
                kind = _is_ctor(node.value, _LOCK_CTORS)
                if kind is not None:
                    cls.locks[tgt.attr] = kind
                elif _is_thread_ctor(node.value):
                    cls.threads.add(tgt.attr)
                elif _is_ctor(node.value, _QUEUE_CTORS) is not None:
                    cls.queues.add(tgt.attr)
                elif in_init and isinstance(node.value, ast.Call):
                    d = dotted_name(node.value.func)
                    if d is not None:
                        last = d.rsplit(".", 1)[-1]
                        if last[:1].isupper():
                            cls.collab[tgt.attr] = last


def _collect_module(path, root) -> Optional[_Mod]:
    src, tree = parse_file(path)
    if tree is None:
        return None
    mod = _Mod(rel(path, root))
    mod.supp = suppressed_lines(src)
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "jax" or alias.name.startswith("jax."):
                    mod.jax_aliases.add(
                        alias.asname or alias.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            if node.module and (node.module == "jax"
                                or node.module.startswith("jax.")):
                for alias in node.names:
                    mod.jax_aliases.add(alias.asname or alias.name)
    # phase 1: module-level locks and per-class attribute classification
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign):
            kind = _is_ctor(stmt.value, _LOCK_CTORS)
            if kind is not None:
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        mod.mod_locks[tgt.id] = kind
        elif isinstance(stmt, ast.ClassDef):
            cls = _Cls(mod, stmt.name, stmt.lineno)
            mod.classes[stmt.name] = cls
            for base in stmt.bases:
                b = dotted_name(base) or ""
                if "HTTPRequestHandler" in b:
                    cls.http_handler = True
            _prescan_class(stmt, cls)
    # phase 2: full body walk with complete lock/thread/queue sets
    for stmt in tree.body:
        if isinstance(stmt, ast.ClassDef):
            cls = mod.classes[stmt.name]
            for sub in stmt.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    _collect_function(
                        sub, cls, mod, in_init=(sub.name == "__init__"))
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _collect_function(stmt, None, mod)
    return mod


# ------------------------------------------------------------ resolution


class _Graph:
    """The resolved cross-module call/lock/blocking view."""

    def __init__(self, mods: List[_Mod]):
        self.mods = mods
        self.classes: Dict[str, _Cls] = {}
        for m in mods:
            for c in m.classes.values():
                self.classes.setdefault(c.name, c)
        self._lock_clo: Dict[int, set] = {}
        self._blk_clo: Dict[int, set] = {}
        self._jax_clo: Dict[int, bool] = {}

    def resolve(self, caller: _Meth, desc: tuple) -> Optional[_Meth]:
        kind = desc[0]
        cls = caller.owner if isinstance(caller.owner, _Cls) else None
        if kind == "self" and cls is not None:
            return cls.meths.get(desc[1])
        if kind == "attr" and cls is not None:
            cname = cls.collab.get(desc[1])
            if cname is None:
                return None
            target = caller.mod.classes.get(cname) or self.classes.get(cname)
            if target is None:
                return None
            return target.meths.get(desc[2])
        if kind == "name":
            # nested defs shadow module functions: try the caller's own
            # prefix chain first ("start.loop" from inside "start")
            table = cls.meths if cls is not None else caller.mod.funcs
            parts = caller.name.split(".")
            for i in range(len(parts), 0, -1):
                hit = table.get(".".join(parts[:i]) + "." + desc[1])
                if hit is not None:
                    return hit
            return caller.mod.funcs.get(desc[1])
        return None

    def _closure(self, meth: _Meth, cache: dict, collect, stack=None) -> set:
        key = id(meth)
        if key in cache:
            return cache[key]
        stack = stack or set()
        if key in stack:
            return set()
        stack = stack | {key}
        out = set(collect(meth))
        for desc, _line, _held in meth.calls:
            callee = self.resolve(meth, desc)
            if callee is not None:
                out |= self._closure(callee, cache, collect, stack)
        cache[key] = out
        return out

    def lock_closure(self, meth: _Meth) -> set:
        return self._closure(
            meth, self._lock_clo,
            lambda m: {lid for lid, _l, _h in m.acquires})

    def blocking_closure(self, meth: _Meth) -> set:
        return self._closure(
            meth, self._blk_clo,
            lambda m: {(what, m.qualname, line)
                       for what, line, _h in m.blocking})

    def reaches_jax(self, meth: _Meth) -> bool:
        key = id(meth)
        if key not in self._jax_clo:
            self._jax_clo[key] = bool(self._closure(
                meth, {}, lambda m: {1} if m.jax_local else set()))
        return self._jax_clo[key]

    def reach_set(self, roots: Sequence[_Meth]) -> set:
        seen = set()
        todo = list(roots)
        while todo:
            m = todo.pop()
            if id(m) in seen:
                continue
            seen.add(id(m))
            for desc, _line, _held in m.calls:
                callee = self.resolve(m, desc)
                if callee is not None and id(callee) not in seen:
                    todo.append(callee)
        return seen

    def lock_kind(self, lock_id: str) -> str:
        owner, attr = lock_id.split(".", 1)
        cls = self.classes.get(owner)
        if cls is not None:
            return cls.locks.get(attr, "Lock")
        for m in self.mods:
            if m.stem == owner:
                return m.mod_locks.get(attr, "Lock")
        return "Lock"


# -------------------------------------------------------------- the rules


def _discover_roots(g: _Graph) -> Dict[str, set]:
    """{root label: set(id(meth) reachable)} for every discovered entry
    point plus one shared "api" root (the external caller thread of any
    class that owns an entry point)."""
    roots: Dict[str, List[_Meth]] = {}
    api_classes = set()
    for mod in g.mods:
        for kind, cls, caller, desc, _line in mod.entries:
            target = g.resolve(caller, desc)
            if target is None:
                continue
            label = f"{kind}:{target.qualname}"
            roots.setdefault(label, []).append(target)
            if isinstance(target.owner, _Cls):
                api_classes.add(id(target.owner))
            if isinstance(caller.owner, _Cls):
                api_classes.add(id(caller.owner))
        for cls in mod.classes.values():
            if cls.http_handler:
                for name, meth in cls.meths.items():
                    if name.startswith("do_"):
                        roots.setdefault(f"http:{meth.qualname}", []) \
                            .append(meth)
                        api_classes.add(id(cls))
    api_roots: List[_Meth] = []
    for mod in g.mods:
        for cls in mod.classes.values():
            if id(cls) not in api_classes:
                continue
            for name, meth in cls.meths.items():
                top = name.split(".", 1)[0]
                if not top.startswith("_") or top in ("__enter__",
                                                      "__exit__"):
                    api_roots.append(meth)
    out = {label: g.reach_set(ms) for label, ms in roots.items()}
    if api_roots:
        out["api"] = g.reach_set(api_roots)
    return out


def _conc001(g: _Graph, out: List[Finding]):
    reach = _discover_roots(g)
    for mod in g.mods:
        for cls in mod.classes.values():
            by_attr: Dict[str, list] = {}
            for meth in cls.meths.values():
                for attr, line, held in meth.writes:
                    by_attr.setdefault(attr, []).append((meth, line, held))
            for attr, writes in sorted(by_attr.items()):
                write_roots = set()
                for meth, _line, _held in writes:
                    for label, members in reach.items():
                        if id(meth) in members:
                            write_roots.add(label)
                if len(write_roots) < 2:
                    continue
                common = frozenset.intersection(
                    *[held for _m, _l, held in writes])
                if common:
                    continue
                bare = min(writes, key=lambda w: len(w[2]))
                sites = ", ".join(sorted(
                    {f"{m.name}:{ln}" for m, ln, _h in writes}))
                out.append(Finding(
                    PASS, "CONC001", mod.path, bare[1],
                    f"{cls.name}.{attr} is written from "
                    f"{len(write_roots)} thread entry points "
                    f"({', '.join(sorted(write_roots))}) without a common "
                    f"lock scope (writes at {sites}) — wrap every write "
                    f"in one `with self._lock:` region or allowlist with "
                    f"a justification"))


def _conc002(g: _Graph, out: List[Finding]):
    edges: Dict[str, Dict[str, tuple]] = {}

    def add_edge(a: str, b: str, witness: tuple):
        if a == b and g.lock_kind(a) == "RLock":
            return
        edges.setdefault(a, {}).setdefault(b, witness)

    for mod in g.mods:
        meths = list(mod.funcs.values())
        for cls in mod.classes.values():
            meths.extend(cls.meths.values())
        for meth in meths:
            for lid, line, held in meth.acquires:
                for h in held:
                    add_edge(h, lid, (mod.path, line, meth.qualname, None))
            for desc, line, held in meth.calls:
                if not held:
                    continue
                callee = g.resolve(meth, desc)
                if callee is None:
                    continue
                for lid in g.lock_closure(callee):
                    for h in held:
                        add_edge(h, lid,
                                 (mod.path, line, meth.qualname,
                                  callee.qualname))

    # cycle detection: DFS, each cycle reported once (keyed on node set)
    seen_cycles = set()
    WHITE, GREY, BLACK = 0, 1, 2
    color: Dict[str, int] = {}

    def report(nodes, wits):
        key = frozenset(nodes)
        if key in seen_cycles:
            return
        seen_cycles.add(key)
        hops = []
        for i, w in enumerate(wits):
            via = f" via {w[3]}" if w[3] else ""
            hops.append(f"{nodes[i]} -> {nodes[i + 1]} "
                        f"({w[0]}:{w[1]} in {w[2]}{via})")
        out.append(Finding(
            PASS, "CONC002", wits[-1][0], wits[-1][1],
            "lock-order cycle: " + "; ".join(hops)
            + " — pick one global acquisition order or move the inner "
              "acquisition outside the outer region"))

    def dfs(node, path, wits):
        color[node] = GREY
        for nxt in sorted(edges.get(node, {})):
            w = edges[node][nxt]
            if color.get(nxt, WHITE) == GREY:
                start = path.index(nxt)
                report(path[start:] + [nxt], wits[start:] + [w])
            elif color.get(nxt, WHITE) == WHITE:
                dfs(nxt, path + [nxt], wits + [w])
        color[node] = BLACK

    for n in sorted(edges):
        if color.get(n, WHITE) == WHITE:
            dfs(n, [n], [])


def _conc003(g: _Graph, out: List[Finding]):
    for mod in g.mods:
        meths = list(mod.funcs.values())
        for cls in mod.classes.values():
            meths.extend(cls.meths.values())
        for meth in meths:
            for what, line, held in meth.blocking:
                if held:
                    out.append(Finding(
                        PASS, "CONC003", mod.path, line,
                        f"known-blocking call [{what}] in {meth.qualname} "
                        f"while holding {', '.join(sorted(held))} — move "
                        f"it outside the lock region (collect under the "
                        f"lock, act outside)"))
            for desc, line, held in meth.calls:
                if not held:
                    continue
                callee = g.resolve(meth, desc)
                if callee is None:
                    continue
                for what, where, bline in sorted(g.blocking_closure(callee)):
                    out.append(Finding(
                        PASS, "CONC003", mod.path, line,
                        f"call to {callee.qualname} in {meth.qualname} "
                        f"while holding {', '.join(sorted(held))} reaches "
                        f"known-blocking [{what}] at {where}:{bline} — "
                        f"move the call outside the lock region"))


def _conc004(g: _Graph, out: List[Finding]):
    for mod in g.mods:
        for meth, line, daemon, name, descs in mod.spawns:
            if not daemon:
                continue
            for desc in descs:
                target = g.resolve(meth, desc)
                if target is not None and g.reaches_jax(target):
                    label = name or "<unnamed>"
                    out.append(Finding(
                        PASS, "CONC004", mod.path, line,
                        f"daemon thread {label!r} (target "
                        f"{target.qualname}) can reach jax — the "
                        f"interpreter kills daemon threads mid-device-"
                        f"call at exit (teardown segfault class); make "
                        f"it non-daemon with a bounded join on the "
                        f"shutdown path, or allowlist with the "
                        f"abandonment contract spelled out"))


# -------------------------------------------------------------- allowlist


def load_allowlist(path=None) -> List[dict]:
    p = Path(path) if path is not None else ALLOWLIST_PATH
    if not p.exists():
        return []
    return json.loads(p.read_text())


def _apply_allowlist(findings: List[Finding], allowlist: List[dict],
                     check_stale: bool) -> List[Finding]:
    out: List[Finding] = []
    used = [False] * len(allowlist)
    for i, entry in enumerate(allowlist):
        if not str(entry.get("why", "")).strip():
            out.append(Finding(
                PASS, "CONC000", str(ALLOWLIST_PATH.name), i + 1,
                f"allowlist entry {i} ({entry.get('rule')}, "
                f"{entry.get('match')!r}) has no written justification — "
                f"every entry needs a non-empty 'why'"))
            used[i] = True  # don't double-report as stale
    for f in findings:
        allowed = False
        for i, entry in enumerate(allowlist):
            if entry.get("rule") != f.code:
                continue
            if entry.get("path") and not f.path.endswith(entry["path"]):
                continue
            if entry.get("match") and entry["match"] not in f.message:
                continue
            if not str(entry.get("why", "")).strip():
                continue
            allowed, used[i] = True, True
            break
        if not allowed:
            out.append(f)
    if check_stale:
        for i, entry in enumerate(allowlist):
            if not used[i]:
                out.append(Finding(
                    PASS, "CONC000", str(ALLOWLIST_PATH.name), i + 1,
                    f"stale allowlist entry {i}: rule={entry.get('rule')} "
                    f"path={entry.get('path')!r} "
                    f"match={entry.get('match')!r} matched no finding — "
                    f"the pattern it justified is gone; delete the entry"))
    return out


# -------------------------------------------------------------- entry


def _default_files(root) -> List[Path]:
    root = Path(root)
    out = []
    for pkg in _SCOPE_PKGS:
        base = root / "alphafold2_tpu" / pkg
        if base.is_dir():
            out.extend(sorted(base.rglob("*.py")))
    return out


def lock_graph(root, files=None) -> Dict[str, Dict[str, tuple]]:
    """The static lock-acquisition graph {held: {acquired: witness}} —
    exported for docs tooling and for comparing against the runtime
    graph from analysis/lock_runtime.py."""
    mods = [m for m in (_collect_module(p, root)
                        for p in (files or _default_files(root)))
            if m is not None]
    g = _Graph(mods)
    edges: Dict[str, Dict[str, tuple]] = {}
    for mod in g.mods:
        meths = list(mod.funcs.values())
        for cls in mod.classes.values():
            meths.extend(cls.meths.values())
        for meth in meths:
            for lid, line, held in meth.acquires:
                for h in held:
                    edges.setdefault(h, {}).setdefault(
                        lid, (mod.path, line))
            for desc, line, held in meth.calls:
                if not held:
                    continue
                callee = g.resolve(meth, desc)
                if callee is None:
                    continue
                for lid in g.lock_closure(callee):
                    for h in held:
                        edges.setdefault(h, {}).setdefault(
                            lid, (mod.path, line))
    return edges


def run(root, files: Optional[Sequence] = None,
        allowlist: Optional[Sequence] = None) -> List[Finding]:
    """Run the concurrency pass. `files` restricts the analyzed universe
    (fixture injection); `allowlist` overrides the default JSON (a list
    of {"rule", "path", "match", "why"} dicts)."""
    paths = [Path(f) for f in files] if files is not None \
        else _default_files(root)
    mods = []
    findings: List[Finding] = []
    for p in paths:
        if not str(p).endswith(".py"):
            continue
        try:
            m = _collect_module(p, root)
        except (OSError, ValueError):
            continue
        if m is None:
            findings.append(Finding(
                PASS, "CONC000", rel(p, root), 1,
                "file does not parse; concurrency analysis skipped"))
            continue
        mods.append(m)
    g = _Graph(mods)
    _conc001(g, findings)
    _conc002(g, findings)
    _conc003(g, findings)
    _conc004(g, findings)
    per_file_supp = {m.path: m.supp for m in mods}
    findings = [
        f for f in findings
        if f.path not in per_file_supp
        or f in filter_suppressed([f], per_file_supp[f.path])
    ]
    check_stale = allowlist is not None or files is None
    wl = list(allowlist) if allowlist is not None else load_allowlist()
    findings = _apply_allowlist(findings, wl, check_stale)
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings
