"""Pass 1 — JAX compat linter.

Two invariants, both AST-checked over every .py file in the repo except
`alphafold2_tpu/compat.py` (the single module allowed to touch
version-dependent names):

  COMPAT001  no `jax.experimental.*` import or attribute access — the
             experimental namespace is where JAX renames things without
             deprecation cycles; every use funnels through compat.py.
  COMPAT002  no direct use of a drift-table symbol (drift.py) under
             EITHER of its spellings: `pltpu.CompilerParams` is exactly
             as wrong as `pltpu.TPUCompilerParams` — one of the two
             crashes on the JAX you are not testing on today.
  COMPAT003  no drifted call keyword (`check_vma`/`check_rep`,
             `ShapeDtypeStruct(vma=...)`) except on the compat wrappers
             that normalize them.

Suppression: `# af2lint: disable=COMPAT002` on the offending line (used
by code that is itself version-probing, which should be rare — prefer
moving the probe into compat.py).
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List, Optional, Sequence

from alphafold2_tpu.analysis import drift
from alphafold2_tpu.analysis.common import (
    Finding,
    dotted_name,
    filter_suppressed,
    iter_py_files,
    parse_file,
    rel,
    suppressed_lines,
)

PASS = "compat"

# the one module allowed to spell version-dependent names
_EXEMPT_FILES = {("alphafold2_tpu", "compat.py")}

_EXPERIMENTAL_PREFIX = "jax.experimental"


def _is_exempt(path: Path) -> bool:
    parts = tuple(Path(path).parts[-2:])
    return parts in _EXEMPT_FILES


def _contains_compat_ref(node: ast.AST, attr: str, aliases: dict) -> bool:
    """True if any descendant resolves to the compat wrapper `attr`:
    `compat.<attr>`, or a bare name imported from alphafold2_tpu.compat
    (`from alphafold2_tpu.compat import shard_map`). Lets both
    `functools.partial(compat.shard_map, ..., check_vma=False)` and the
    direct `shard_map(..., check_vma=False)` through."""
    for sub in ast.walk(node):
        if (
            isinstance(sub, ast.Attribute)
            and sub.attr == attr
            and isinstance(sub.value, ast.Name)
            and sub.value.id == "compat"
        ):
            return True
        if isinstance(sub, ast.Name) and aliases.get(sub.id) == attr:
            return True
    return False


class _Visitor(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        self.findings: List[Finding] = []
        self._seen = set()
        self._attr_idx = drift.attr_index()
        self._kw_idx = drift.keyword_index()
        self._full_names = {
            n for e in drift.DRIFT_TABLE for n in e.full_names
        }
        # local alias -> compat attribute, for names imported from compat
        self._compat_aliases: dict = {}

    def _emit(self, code: str, line: int, message: str):
        key = (code, line)
        if key in self._seen:
            return
        self._seen.add(key)
        self.findings.append(Finding(PASS, code, self.path, line, message))

    # --- imports ---------------------------------------------------------
    def visit_Import(self, node: ast.Import):
        for alias in node.names:
            if alias.name == _EXPERIMENTAL_PREFIX or alias.name.startswith(
                _EXPERIMENTAL_PREFIX + "."
            ):
                self._emit(
                    "COMPAT001",
                    node.lineno,
                    f"import of {alias.name!r}: jax.experimental access is "
                    "reserved to alphafold2_tpu/compat.py",
                )
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom):
        mod = node.module or ""
        if mod == "alphafold2_tpu.compat":
            for alias in node.names:
                self._compat_aliases[alias.asname or alias.name] = alias.name
        if mod == _EXPERIMENTAL_PREFIX or mod.startswith(_EXPERIMENTAL_PREFIX + "."):
            self._emit(
                "COMPAT001",
                node.lineno,
                f"import from {mod!r}: jax.experimental access is reserved "
                "to alphafold2_tpu/compat.py",
            )
        else:
            for alias in node.names:
                full = f"{mod}.{alias.name}" if mod else alias.name
                if full in self._full_names:
                    entry = next(
                        e for e in drift.DRIFT_TABLE if full in e.full_names
                    )
                    self._emit(
                        "COMPAT002",
                        node.lineno,
                        f"{full!r} is in the drift table "
                        f"({entry.renamed_in}); import {entry.compat_name} "
                        "instead",
                    )
        self.generic_visit(node)

    # --- attribute access ------------------------------------------------
    def visit_Attribute(self, node: ast.Attribute):
        name = dotted_name(node)
        if name:
            if name.startswith(_EXPERIMENTAL_PREFIX + ".") or name == _EXPERIMENTAL_PREFIX:
                self._emit(
                    "COMPAT001",
                    node.lineno,
                    f"attribute access {name!r}: jax.experimental access is "
                    "reserved to alphafold2_tpu/compat.py",
                )
                return  # don't also drift-match suffixes of the same chain
            if name in self._full_names:
                entry = next(
                    e for e in drift.DRIFT_TABLE if name in e.full_names
                )
                self._emit(
                    "COMPAT002",
                    node.lineno,
                    f"{name!r} is in the drift table ({entry.renamed_in}); "
                    f"use {entry.compat_name}",
                )
                return
        entry = self._attr_idx.get(node.attr)
        if entry is not None:
            base = node.value.id if isinstance(node.value, ast.Name) else None
            if base != "compat":
                self._emit(
                    "COMPAT002",
                    node.lineno,
                    f".{node.attr} is in the drift table ({entry.renamed_in}); "
                    f"use {entry.compat_name}",
                )
        self.generic_visit(node)

    # --- drifted call keywords -------------------------------------------
    def visit_Call(self, node: ast.Call):
        for kw in node.keywords:
            entry = self._kw_idx.get(kw.arg or "")
            if entry is None:
                continue
            if kw.arg == "vma":
                # only meaningful on ShapeDtypeStruct construction
                callee = dotted_name(node.func) or ""
                if not callee.endswith("ShapeDtypeStruct"):
                    continue
                self._emit(
                    "COMPAT003",
                    node.lineno,
                    f"ShapeDtypeStruct(vma=...) ({entry.renamed_in}); use "
                    f"{entry.compat_name}",
                )
            else:
                wrapper = entry.compat_name.split(".")[-1]
                if _contains_compat_ref(node, wrapper, self._compat_aliases):
                    continue
                self._emit(
                    "COMPAT003",
                    node.lineno,
                    f"{kw.arg}= keyword ({entry.renamed_in}); call "
                    f"{entry.compat_name}, which normalizes it",
                )
        self.generic_visit(node)


def run(root, files: Optional[Sequence] = None) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_py_files(root, files):
        if _is_exempt(path):
            continue
        src, tree = parse_file(path)
        rpath = rel(path, root)
        if tree is None:
            findings.append(
                Finding(PASS, "COMPAT000", rpath, 1, "file does not parse")
            )
            continue
        v = _Visitor(rpath)
        v.visit(tree)
        findings.extend(filter_suppressed(v.findings, suppressed_lines(src)))
    return findings
