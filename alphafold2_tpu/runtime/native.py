"""ctypes bindings for the C++ host runtime (csrc/af2_runtime.cc).

Build-on-first-use: `g++ -O3 -shared` into a cached .so next to the source.
Everything degrades to pure-Python fallbacks (geometry/pdb.py, the numpy
data pipeline) when the toolchain or the library is unavailable, mirroring
the reference's optional-dependency discipline (reference utils.py:10-21).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Iterator, Optional

import numpy as np

_REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))
_SRC = os.path.join(_REPO_ROOT, "csrc", "af2_runtime.cc")
_LIB = os.path.join(_REPO_ROOT, "csrc", "libaf2runtime.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> Optional[str]:
    if os.path.exists(_LIB) and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC):
        return _LIB
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread", _SRC, "-o", _LIB]
    try:
        res = subprocess.run(cmd, capture_output=True, timeout=300)
    except Exception:
        return None
    if res.returncode != 0:
        return None
    return _LIB


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        path = _build()
        if path is None:
            _build_failed = True
            return None
        lib = ctypes.CDLL(path)
        lib.af2_loader_create.restype = ctypes.c_void_p
        lib.af2_loader_create.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int, ctypes.POINTER(ctypes.c_float), ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_uint64,
            ctypes.c_int, ctypes.c_int,
        ]
        lib.af2_loader_create2.restype = ctypes.c_void_p
        lib.af2_loader_create2.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int, ctypes.POINTER(ctypes.c_float), ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_uint64,
            ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
        ]
        lib.af2_loader_next.restype = ctypes.c_int
        lib.af2_loader_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_float),
        ]
        lib.af2_loader_destroy.argtypes = [ctypes.c_void_p]
        lib.af2_parse_pdb.restype = ctypes.c_int
        lib.af2_parse_pdb.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float), ctypes.c_char_p,
        ]
        lib.af2_write_pdb.restype = ctypes.c_int64
        lib.af2_write_pdb.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float), ctypes.c_char_p, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int64,
        ]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------------
# Prefetch loader
# ---------------------------------------------------------------------------


class NativePrefetchLoader:
    """Threaded C++ batch loader over an in-memory structure dataset.

    Dataset: list of (seq_tokens (L,), coords (L, atoms, 3)) pairs of
    arbitrary lengths. Workers shuffle, random-crop to `max_len`, pad, and
    assemble static-shape batches off the GIL into a bounded queue.

    Iterating yields {"seq": (b, max_len) int32, "mask": (b, max_len) bool,
    "coords": (b, max_len, atoms, 3) float32} — the train_pre/e2e batch
    contract (coords sliced to (b, L, 3) by the caller when only C-alpha is
    needed).

    Falls back to a single-threaded numpy implementation when the native
    library is unavailable (`self.native` False).
    """

    def __init__(self, dataset, batch_size: int, max_len: int,
                 atoms_per_res: int = 14, pad_token: int = 20, seed: int = 0,
                 n_threads: int = 2, queue_capacity: int = 4,
                 buckets: Optional[tuple] = None):
        if not dataset:
            raise ValueError("NativePrefetchLoader needs a non-empty dataset")
        if buckets:
            buckets = tuple(sorted(set(int(x) for x in buckets)))
            if max_len != buckets[-1]:
                raise ValueError(
                    f"max_len ({max_len}) must equal the largest bucket "
                    f"({buckets[-1]}) — the top bucket IS the crop length"
                )
        self.buckets = buckets or None
        self.batch = batch_size
        self.max_len = max_len
        self.atoms = atoms_per_res
        self.pad_token = pad_token
        self._handle = None
        self._closed = False

        seqs = [np.asarray(s, np.int32).reshape(-1) for s, _ in dataset]
        coords = [
            np.asarray(c, np.float32).reshape(len(s), atoms_per_res, 3)
            for s, (_, c) in zip(seqs, dataset)
        ]
        self._offsets = np.zeros(len(seqs) + 1, np.int64)
        np.cumsum([len(s) for s in seqs], out=self._offsets[1:])
        self._seqs = np.concatenate(seqs) if seqs else np.zeros(0, np.int32)
        self._coords = (
            np.concatenate(coords).reshape(-1) if coords else np.zeros(0, np.float32)
        )

        lib = _load()
        if lib is not None:
            self._lib = lib
            bk = np.asarray(self.buckets or (), np.int32)
            self._handle = lib.af2_loader_create2(
                self._seqs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                self._offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                len(seqs),
                self._coords.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                atoms_per_res, batch_size, self.max_len, pad_token, seed,
                n_threads, queue_capacity,
                bk.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), len(bk),
            )
        if self._handle is None:
            # pure-python fallback
            self._rng = np.random.RandomState(seed)
            self._pending = {bl: [] for bl in (self.buckets or ())}

    @property
    def native(self) -> bool:
        return self._handle is not None

    def __iter__(self) -> Iterator[dict]:
        while True:
            yield self.next()

    def next(self) -> dict:
        if getattr(self, "_closed", False):
            raise RuntimeError("loader is closed")
        b, L, A = self.batch, self.max_len, self.atoms
        if self._handle is not None:
            # flat max-size buffers; the C++ side writes COMPACT rows at the
            # batch's bucket length and returns it, so the filled prefix
            # reshapes to contiguous (b, bl, ...) arrays with no re-copy
            seq = np.empty(b * L, np.int32)
            mask = np.empty(b * L, np.uint8)
            coords = np.empty(b * L * A * 3, np.float32)
            bl = self._lib.af2_loader_next(
                self._handle,
                seq.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                mask.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                coords.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            )
            out = {
                "seq": seq[: b * bl].reshape(b, bl),
                "mask": mask[: b * bl].reshape(b, bl).astype(bool),
                "coords": coords[: b * bl * A * 3].reshape(b, bl, A, 3),
            }
            if self.buckets:
                out["bucket"] = int(bl)
            return out

        if self.buckets:
            return self._next_bucketed_py()

        seq = np.full((b, L), self.pad_token, np.int32)
        mask = np.zeros((b, L), bool)
        coords = np.zeros((b, L, A, 3), np.float32)
        n_seqs = len(self._offsets) - 1
        for i in range(b):
            idx = self._rng.randint(n_seqs)
            beg, end = self._offsets[idx], self._offsets[idx + 1]
            length = int(end - beg)
            start = self._rng.randint(0, length - L + 1) if length > L else 0
            length = min(length, L)
            sl = slice(int(beg) + start, int(beg) + start + length)
            seq[i, :length] = self._seqs[sl]
            mask[i, :length] = True
            coords[i, :length] = self._coords.reshape(-1, A, 3)[sl]
        return {"seq": seq, "mask": mask, "coords": coords}

    def _next_bucketed_py(self) -> dict:
        """Python-fallback mirror of the C++ bucketed assembly."""
        b, A = self.batch, self.atoms
        n_seqs = len(self._offsets) - 1
        while True:
            idx = self._rng.randint(n_seqs)
            length = int(self._offsets[idx + 1] - self._offsets[idx])
            bl = next((x for x in self.buckets if length <= x), self.buckets[-1])
            self._pending[bl].append(idx)
            if len(self._pending[bl]) < b:
                continue
            group, self._pending[bl] = self._pending[bl], []
            seq = np.full((b, bl), self.pad_token, np.int32)
            mask = np.zeros((b, bl), bool)
            coords = np.zeros((b, bl, A, 3), np.float32)
            for i, idx in enumerate(group):
                beg, end = self._offsets[idx], self._offsets[idx + 1]
                length = int(end - beg)
                start = (
                    self._rng.randint(0, length - bl + 1) if length > bl else 0
                )
                length = min(length, bl)
                sl = slice(int(beg) + start, int(beg) + start + length)
                seq[i, :length] = self._seqs[sl]
                mask[i, :length] = True
                coords[i, :length] = self._coords.reshape(-1, A, 3)[sl]
            return {"seq": seq, "mask": mask, "coords": coords, "bucket": bl}

    def close(self):
        self._closed = True
        if self._handle is not None:
            self._lib.af2_loader_destroy(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# PDB codec
# ---------------------------------------------------------------------------


def parse_pdb_fast(path: str):
    """Parse ATOM records via the C++ codec; returns a
    geometry.pdb.PdbStructure (falls back to the Python parser)."""
    from alphafold2_tpu.geometry.pdb import PdbAtom, PdbStructure, parse_pdb

    lib = _load()
    if lib is None:
        return parse_pdb(path)

    with open(path, "rb") as fh:
        text = fh.read()
    max_atoms = max(1, text.count(b"\nATOM") + (1 if text.startswith(b"ATOM") else 0))
    xyz = np.empty((max_atoms, 3), np.float32)
    res_seq = np.empty(max_atoms, np.int32)
    bfac = np.empty(max_atoms, np.float32)
    names = ctypes.create_string_buffer(8 * max_atoms)
    n = lib.af2_parse_pdb(
        text, len(text), max_atoms,
        xyz.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        res_seq.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        bfac.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        names,
    )
    atoms = []
    raw = names.raw
    for i in range(n):
        rec = raw[i * 8 : i * 8 + 8]
        atoms.append(
            PdbAtom(
                serial=i + 1,
                name=rec[0:4].decode().strip(),
                res_name=rec[4:7].decode().strip(),
                chain_id=(rec[7:8].decode().strip() or "A"),
                res_seq=int(res_seq[i]),
                xyz=xyz[i].astype(np.float64),
                bfactor=float(bfac[i]),
            )
        )
    return PdbStructure(atoms)


def write_pdb_fast(path: str, structure) -> str:
    """Write a PdbStructure via the C++ codec (Python fallback)."""
    from alphafold2_tpu.geometry.pdb import write_pdb

    lib = _load()
    if lib is None:
        return write_pdb(path, structure)

    n = len(structure.atoms)
    xyz = np.asarray([a.xyz for a in structure.atoms], np.float32).reshape(n, 3)
    res_seq = np.asarray([a.res_seq for a in structure.atoms], np.int32)
    bfac = np.asarray([a.bfactor for a in structure.atoms], np.float32)
    names = bytearray(8 * n)
    for i, a in enumerate(structure.atoms):
        nm = a.name if len(a.name) == 4 else f" {a.name:<3s}"
        names[i * 8 : i * 8 + 4] = nm.encode()[:4].ljust(4)
        names[i * 8 + 4 : i * 8 + 7] = a.res_name.encode()[:3].rjust(3)
        names[i * 8 + 7] = (a.chain_id or "A").encode()[0]
    cap = 82 * (n + 1)
    out = ctypes.create_string_buffer(cap)
    written = lib.af2_write_pdb(
        xyz.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        res_seq.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        bfac.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        bytes(names), n, out, cap,
    )
    if written < 0:
        return write_pdb(path, structure)
    with open(path, "wb") as fh:
        fh.write(out.raw[:written])
    return path
