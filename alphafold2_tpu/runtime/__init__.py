"""Native host runtime layer (C++ via ctypes).

See csrc/af2_runtime.cc: threaded prefetch batch loader + PDB codec. All
entry points degrade to pure-Python fallbacks when the native library
cannot be built (no g++), so the framework never hard-requires it.
"""

from alphafold2_tpu.runtime.native import (
    NativePrefetchLoader,
    native_available,
    parse_pdb_fast,
    write_pdb_fast,
)

__all__ = [
    "NativePrefetchLoader",
    "native_available",
    "parse_pdb_fast",
    "write_pdb_fast",
]
