"""End-to-end structure training: distogram -> 3D coords -> refiner -> loss.

This implements the pipeline the reference *intended* in `train_end2end.py`
(which does not run as-is — see the defect list in SURVEY.md §3.2): model
forward on the x3-elongated backbone sequence (train_end2end.py:134-149),
distogram centering (:152), MDS with mirror fix (:154-160), sidechain
container lifting (:163), SE(3)-equivariant refinement (:168-169), Kabsch
alignment (:172) and RMSD + distogram-dispersion loss (:175-176).

Everything is one differentiable jitted graph: gradients flow through the
refiner, the sidechain lift, the Guttman MDS iterations, and the distogram
centering back into the trunk — the same coupling the reference loss
depends on.

Deliberate fixes vs the reference script:
  * elongated residues are fed directly as repeated tokens (the reference's
    `pos_tokens=3` kwarg does not exist on its own model, train_end2end.py:80);
  * `1/weights` in the dispersion term is `1/(weights + eps)` — reference
    divides by exact zeros for censored distogram bins (train_end2end.py:176);
  * Kabsch uses static-shape weighted alignment instead of boolean indexing
    (train_end2end.py:172 breaks under jit; see geometry/kabsch.py).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from alphafold2_tpu.constants import NUM_COORDS_PER_RES
from alphafold2_tpu.geometry import (
    center_distogram,
    kabsch,
    mdscaling,
    scn_backbone_mask,
    scn_cloud_mask,
    sidechain_container,
)
from alphafold2_tpu.models import (
    Alphafold2Config,
    RefinerConfig,
    alphafold2_apply,
    alphafold2_init,
    refiner_apply,
    refiner_init,
)


@dataclasses.dataclass(frozen=True)
class E2EConfig:
    """Hashable config for the full structure workload (BASELINE config 5)."""

    model: Alphafold2Config
    refiner: RefinerConfig = RefinerConfig(num_tokens=NUM_COORDS_PER_RES)
    mds_iters: int = 200  # reference train_end2end.py:157
    # truncate MDS backprop to the last K Guttman iterations (None = full
    # unroll). Near convergence this approximates implicit differentiation
    # (geometry/mds.py) and removes iters-K per-iteration (3L, 3L) residuals
    # from the backward — the MDS unroll is a dominant latency/memory cost
    # at the north-star scale (PERF.md)
    mds_bwd_iters: int | None = None
    # lax.scan unroll factor for the MDS iterations (geometry/mds.py):
    # amortizes per-iteration dispatch overhead on TPU (same math; float
    # reassociation noise only)
    mds_unroll: int = 1
    # "random" (reference parity) or "classical": Torgerson eigendecomposition
    # warm start — reaches the random-init stress floor in ~1 iteration on
    # both exact and distogram-censored real inputs (geometry/mds.py), so
    # pairing it with a small mds_iters removes most of the sequential
    # Guttman tail from the step
    mds_init: str = "random"
    fix_mirror: bool = True  # reference fix_mirror=5 -> boolean here; the
    # reference's int is a retry count for an eigen-fallback that its own
    # mds_torch never triggers (utils.py:637-642)
    place_oxygen: bool = True
    dispersion_weight: float = 0.1  # reference train_end2end.py:176
    weights_eps: float = 1e-3


def elongate(seq, factor: int = 3):
    """Repeat each residue token `factor` times: (b, L) -> (b, L*factor)
    (reference train_end2end.py:134-141 — one token per backbone atom)."""
    return jnp.repeat(seq, factor, axis=-1)


def predict_structure(params, ecfg: E2EConfig, seq, mask=None, rng=None, msa=None, msa_mask=None, embedds=None, templates=None, templates_mask=None, model_apply_fn=None):
    """Full forward: sequence -> refined (b, L, 14, 3) atom cloud.

    params: {"model": ..., "refiner": ...}.

    model_apply_fn: override for the trunk forward with the
    alphafold2_apply signature — e.g. the sequence-parallel apply
    (parallel/train.py sp_e2e_loss_fn). Geometry, MDS, and the refiner
    always run replicated (negligible FLOPs/memory share).

    Returns dict with refined cloud, proto cloud, distogram weights, and the
    atom cloud mask.
    """
    apply_fn = model_apply_fn if model_apply_fn is not None else alphafold2_apply
    b, length = seq.shape
    seq3 = elongate(seq)
    mask3 = elongate(mask) if mask is not None else None

    if rng is not None:
        rng_model, rng_mds = jax.random.split(rng)
    else:
        rng_model, rng_mds = None, jax.random.PRNGKey(0)

    # templates are over the ELONGATED (3L, 3L) pair grid — the trunk's
    # pair axes after the x3 backbone-atom expansion (one token per N/CA/C)
    tmpl_kwargs = (
        {"templates": templates, "templates_mask": templates_mask}
        if templates is not None
        else {}
    )
    logits = apply_fn(
        params["model"], ecfg.model, seq3, msa,
        mask=mask3, msa_mask=msa_mask, embedds=embedds, rng=rng_model,
        **tmpl_kwargs,
    )  # (b, 3L, 3L, buckets)
    # geometry runs in float32 regardless of the trunk's compute dtype:
    # the distogram -> MDS pipeline divides by pairwise distances (Guttman
    # B-matrix) and small weights, which overflows/NaNs in bfloat16
    logits = logits.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    distances, weights = center_distogram(probs)

    # chirality masks over the flat (L*3) backbone atom axis
    n_mask, ca_mask = scn_backbone_mask(seq, l_aa=3)
    coords, _ = mdscaling(
        distances,
        weights=weights,
        iters=ecfg.mds_iters,
        fix_mirror=ecfg.fix_mirror,
        N_mask=n_mask,
        CA_mask=ca_mask,
        key=rng_mds,
        bwd_iters=ecfg.mds_bwd_iters,
        unroll=ecfg.mds_unroll,
        init=ecfg.mds_init,
    )  # (b, 3, 3L)

    backbone = jnp.transpose(coords, (0, 2, 1))  # (b, 3L, 3)
    proto = sidechain_container(backbone, place_oxygen=ecfg.place_oxygen)  # (b, L, 14, 3)

    cloud_mask = scn_cloud_mask(seq)  # (b, L, 14)
    if mask is not None:
        cloud_mask = cloud_mask & mask[..., None]

    num_atoms = length * NUM_COORDS_PER_RES
    atom_tokens = jnp.broadcast_to(
        jnp.arange(NUM_COORDS_PER_RES)[None, None, :], cloud_mask.shape
    ).reshape(b, num_atoms)
    refined, _ = refiner_apply(
        params["refiner"], ecfg.refiner,
        atom_tokens, proto.reshape(b, num_atoms, 3),
        mask=cloud_mask.reshape(b, num_atoms),
    )
    return {
        "refined": refined.reshape(b, length, NUM_COORDS_PER_RES, 3),
        "proto": proto,
        "distogram_weights": weights,
        "cloud_mask": cloud_mask,
        "distogram_logits": logits,
    }


def make_e2e_loss_fn(model_apply_fn=None):
    """Build the e2e structure loss around any model apply function — ONE
    loss construction shared by the replicated and sequence-parallel paths
    (parallel/train.py sp_e2e_loss_fn)."""

    def loss_fn(params, ecfg: E2EConfig, batch, rng):
        """Kabsch-aligned RMSD + dispersion loss on one microbatch
        (reference train_end2end.py:172-176).

        batch: {"seq": (b, L) int, "mask": (b, L) bool,
                "coords": (b, L, 14, 3) ground-truth atom cloud,
                optional "atom_mask": (b, L, 14) bool — per-atom resolution
                (sidechainnet zero-pads unresolved atoms; without this they
                would enter the loss as ground truth at the origin)}.
        """
        out = predict_structure(
            params, ecfg, batch["seq"], mask=batch.get("mask"), rng=rng,
            msa=batch.get("msa"), msa_mask=batch.get("msa_mask"),
            embedds=batch.get("embedds"), model_apply_fn=model_apply_fn,
        )
        b, length = batch["seq"].shape
        num_atoms = length * NUM_COORDS_PER_RES
        w = out["cloud_mask"].reshape(b, num_atoms).astype(jnp.float32)
        atom_mask = batch.get("atom_mask")
        if atom_mask is not None:
            w = w * atom_mask.reshape(b, num_atoms).astype(jnp.float32)

        pred = jnp.transpose(out["refined"].reshape(b, num_atoms, 3), (0, 2, 1))
        true = jnp.transpose(
            jnp.asarray(batch["coords"], jnp.float32).reshape(b, num_atoms, 3),
            (0, 2, 1),
        )
        pred_aligned, true_centered = kabsch(pred, true, weights=w)

        sq = jnp.sum(jnp.square(pred_aligned - true_centered), axis=-2)  # (b, A)
        denom = jnp.maximum(jnp.sum(w, axis=-1), 1.0)
        rmsd = jnp.sqrt(jnp.sum(sq * w, axis=-1) / denom)  # (b,)

        # dispersion penalty over UNCENSORED pairs only: censored pairs
        # (weight hard-zeroed by center_distogram for beyond-last-bucket
        # predictions) would add a huge ~1/eps constant with exactly zero
        # gradient, drowning the RMSD signal in the reported loss
        dw = out["distogram_weights"]
        valid = (dw > 0).astype(jnp.float32)
        per_pair = jnp.abs(1.0 / (dw + ecfg.weights_eps) - 1.0) * valid
        dispersion = jnp.sum(per_pair) / jnp.maximum(jnp.sum(valid), 1.0)
        return jnp.mean(rmsd) + ecfg.dispersion_weight * dispersion

    return loss_fn


# the default (replicated-model) e2e loss
e2e_loss_fn = make_e2e_loss_fn()


def e2e_params_init(key, ecfg: E2EConfig):
    """Joint (trunk, refiner) param pytree — the params-only init
    inference entry points use (no optimizer moments allocated)."""
    k1, k2 = jax.random.split(key)
    return {
        "model": alphafold2_init(k1, ecfg.model),
        "refiner": refiner_init(k2, ecfg.refiner),
    }


def e2e_train_state_init(key, ecfg: E2EConfig, tcfg):
    """TrainState over the joint (trunk, refiner) param pytree."""
    from alphafold2_tpu.ops.quant import reject_quant_training
    from alphafold2_tpu.training.harness import make_optimizer

    # int8 weights are the inference-only serving arm (ops/quant.py)
    reject_quant_training(ecfg, "e2e_train_state_init")
    params = e2e_params_init(key, ecfg)
    opt = make_optimizer(tcfg)
    return {"params": params, "opt_state": opt.init(params), "step": jnp.zeros((), jnp.int32)}
