"""Training losses.

Distogram pretraining loss re-designed from the reference driver
(reference train_pre.py:35-40, 91-95): pairwise C-alpha distances are
bucketized into the 37 distogram bins (linspace 2..20) and the model's
distogram logits are scored with masked cross-entropy. Everything is pure
jnp on static shapes — masking replaces the reference's `ignore_index`
tensor sentinel at the loss level.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from alphafold2_tpu.constants import DISTOGRAM_BUCKETS

IGNORE_INDEX = -100  # reference train_pre.py:18


def bucketed_distance_matrix(
    coords,
    mask,
    num_buckets: int = DISTOGRAM_BUCKETS,
    ignore_index: int = IGNORE_INDEX,
):
    """Discretize pairwise distances into distogram buckets.

    Args:
      coords: (b, L, 3) C-alpha coordinates.
      mask: (b, L) bool residue validity.

    Returns: (b, L, L) int32 bucket labels, `ignore_index` where either
      residue is masked (reference train_pre.py:35-40).
    """
    diff = coords[:, :, None, :] - coords[:, None, :, :]
    distances = jnp.sqrt(jnp.maximum(jnp.sum(diff * diff, axis=-1), 1e-12))
    boundaries = jnp.linspace(2.0, 20.0, num_buckets)[:-1]
    # torch.bucketize(right=False): boundaries[i-1] < v <= boundaries[i]
    disc = jnp.searchsorted(boundaries, distances, side="left").astype(jnp.int32)
    pair_mask = mask[:, :, None] & mask[:, None, :]
    return jnp.where(pair_mask, disc, ignore_index)


def distogram_cross_entropy(logits, labels, ignore_index: int = IGNORE_INDEX):
    """Mean cross-entropy over valid pairs (reference train_pre.py:91-95).

    Args:
      logits: (b, n, n, num_buckets).
      labels: (b, n, n) int, `ignore_index` marks pairs to skip.
    """
    valid = labels != ignore_index
    safe = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    total = jnp.sum(nll * valid)
    count = jnp.maximum(jnp.sum(valid), 1)
    return total / count
