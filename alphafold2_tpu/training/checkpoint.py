"""Checkpoint / resume subsystem (orbax-backed).

The reference has NO checkpointing at all — zero `torch.save`/`state_dict`
call sites; its training runs are fire-and-forget 1e5-step loops
(reference train_pre.py:15,72; SURVEY.md §5). For a real framework this is
a gap to fill, not behavior to match: this module wraps orbax's
CheckpointManager so the TrainState pytree (params, opt state, step) is
saved asynchronously, restored *into its sharded layout* on any mesh, and
rotated with a bounded number of retained steps.

Design notes:
  * save is async (orbax default) — the train loop is not blocked on I/O;
    `close()` / context-manager exit drains pending writes.
  * restore takes an optional abstract state (from `jax.eval_shape` +
    shardings), so a checkpoint written on one mesh restores sharded onto
    another — the TPU answer to torch's map_location.
  * step numbering comes from the state itself (`state["step"]`), keeping
    directory names and training steps in lockstep.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np

try:  # orbax is in the baked image; keep a clear error if it is not
    import orbax.checkpoint as ocp
except Exception as e:  # pragma: no cover
    ocp = None
    _import_error = e


class CheckpointManager:
    """Thin lifecycle wrapper over orbax for TrainState pytrees."""

    def __init__(self, directory: str, max_to_keep: int = 3, save_interval_steps: int = 1):
        if ocp is None:  # pragma: no cover
            raise ImportError(f"orbax.checkpoint unavailable: {_import_error}")
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=True,
            ),
        )

    # -- save ---------------------------------------------------------------

    def save(self, state: Any, step: Optional[int] = None, force: bool = False) -> bool:
        """Queue an async save of `state` at `step` (default: state['step'])."""
        if step is None:
            step = int(np.asarray(jax.device_get(state["step"])))
        return self._mgr.save(step, args=ocp.args.StandardSave(state), force=force)

    # -- restore ------------------------------------------------------------

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, abstract_state: Any = None, step: Optional[int] = None) -> Any:
        """Restore a checkpoint.

        Args:
          abstract_state: pytree of jax.ShapeDtypeStruct (optionally with
            .sharding set) describing the target layout; None restores
            host-side numpy arrays.
          step: which step to load (default: latest).
        """
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found under {self.directory}")
        if abstract_state is None:
            return self._mgr.restore(step)
        return self._mgr.restore(step, args=ocp.args.StandardRestore(abstract_state))

    # -- lifecycle ----------------------------------------------------------

    def wait(self):
        """Block until queued async saves hit disk."""
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def abstract_like(state: Any, shardings: Any = None):
    """ShapeDtypeStruct skeleton of `state` for sharded restore.

    `shardings`: matching pytree of jax.sharding.Sharding (e.g. from
    parallel.state_shardings) or None for unspecified placement.
    """
    shapes = jax.eval_shape(lambda s: s, state)
    if shardings is None:
        return shapes
    return jax.tree_util.tree_map(
        lambda sds, sh: jax.ShapeDtypeStruct(sds.shape, sds.dtype, sharding=sh),
        shapes,
        shardings,
    )


def restore_or_init(mgr: CheckpointManager, init_fn, *init_args, shardings: Any = None):
    """Resume-from-latest or cold-start: the standard top-of-loop idiom.

    Returns (state, resumed: bool).
    """
    step = mgr.latest_step()
    if step is None:
        return init_fn(*init_args), False
    # shapes only — no param materialization on the resume path
    template = jax.eval_shape(lambda: init_fn(*init_args))
    return mgr.restore(abstract_like(template, shardings), step=step), True


def open_or_init(
    ckpt_dir: Optional[str],
    init_fn,
    *init_args,
    save_every: int = 1,
    shardings: Any = None,
):
    """Entry-script idiom shared by train_pre.py / train_end2end.py.

    Returns (mgr, state, resumed); mgr is None when ckpt_dir is None.
    Interval gating is delegated to orbax's save_interval_steps — call
    `mgr.save(state)` every step and orbax decides.
    """
    if ckpt_dir is None:
        return None, init_fn(*init_args), False
    mgr = CheckpointManager(ckpt_dir, save_interval_steps=max(1, save_every))
    state, resumed = restore_or_init(mgr, init_fn, *init_args, shardings=shardings)
    return mgr, state, resumed


def restore_params_for_inference(ckpt_dir: Optional[str], init_fn, *init_args,
                                 cold_params_fn=None):
    """Inference-entry idiom shared by predict.py and serve.py: restore the
    latest checkpoint read-only (no manager kept open, nothing to flush),
    fall back to fresh init with a warning.

    `init_fn(*init_args)` is the TRAIN-state init matching the checkpoint
    layout; on the restore path it is only eval_shape'd (restore_or_init),
    so optimizer moments are never materialized. `cold_params_fn()` is the
    params-only init for the no-checkpoint path — without it the cold
    start would materialize (and immediately discard) the full opt state,
    ~2x parameter memory under Adam.

    Returns (params, step, resumed) — step is 0 when cold-started. Callers
    use `f"{ckpt_dir}@step{step}"` as the result-cache fingerprint.
    """
    def cold_params():
        if cold_params_fn is not None:
            return cold_params_fn()
        return init_fn(*init_args)["params"]

    if ckpt_dir is None:
        print("no --ckpt-dir: using randomly initialized params")
        return cold_params(), 0, False
    with CheckpointManager(ckpt_dir) as mgr:
        # probe before delegating to restore_or_init: its cold branch
        # materializes the full train state, which would defeat
        # cold_params_fn on an empty/not-yet-written checkpoint dir
        if mgr.latest_step() is None:
            print(f"warning: no checkpoint in {ckpt_dir}; random params")
            return cold_params(), 0, False
        state, _ = restore_or_init(mgr, init_fn, *init_args)
    step = int(np.asarray(jax.device_get(state["step"])))
    print(f"restored step-{step} params from {ckpt_dir}")
    return state["params"], step, True


def finish(mgr: Optional["CheckpointManager"], state: Any):
    """Final flush at end of training: save the last step if the periodic
    cadence missed it, then drain and close."""
    if mgr is None:
        return
    step = int(np.asarray(jax.device_get(state["step"])))
    if mgr.latest_step() != step:
        mgr.save(state, force=True)
    mgr.close()
