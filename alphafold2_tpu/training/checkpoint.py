"""Checkpoint / resume subsystem (orbax-backed).

The reference has NO checkpointing at all — zero `torch.save`/`state_dict`
call sites; its training runs are fire-and-forget 1e5-step loops
(reference train_pre.py:15,72; SURVEY.md §5). For a real framework this is
a gap to fill, not behavior to match: this module wraps orbax's
CheckpointManager so the TrainState pytree (params, opt state, step) is
saved asynchronously, restored *into its sharded layout* on any mesh, and
rotated with a bounded number of retained steps.

Design notes:
  * save is async (orbax default) — the train loop is not blocked on I/O;
    `close()` / context-manager exit drains pending writes.
  * restore takes an optional abstract state (from `jax.eval_shape` +
    shardings), so a checkpoint written on one mesh restores sharded onto
    another — the TPU answer to torch's map_location.
  * step numbering comes from the state itself (`state["step"]`), keeping
    directory names and training steps in lockstep.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
from typing import Any, Callable, Optional

import jax
import numpy as np

from alphafold2_tpu import compat

try:  # orbax is in the baked image; keep a clear error if it is not
    import orbax.checkpoint as ocp
except Exception as e:  # pragma: no cover
    ocp = None
    _import_error = e


class CheckpointManager:
    """Thin lifecycle wrapper over orbax for TrainState pytrees."""

    def __init__(self, directory: str, max_to_keep: int = 3, save_interval_steps: int = 1):
        if ocp is None:  # pragma: no cover
            raise ImportError(f"orbax.checkpoint unavailable: {_import_error}")
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._closed = False
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=True,
            ),
        )

    # -- save ---------------------------------------------------------------

    def save(self, state: Any, step: Optional[int] = None, force: bool = False) -> bool:
        """Queue an async save of `state` at `step` (default: state['step'])."""
        if step is None:
            step = int(np.asarray(jax.device_get(state["step"])))
        return self._mgr.save(step, args=ocp.args.StandardSave(state), force=force)

    # -- restore ------------------------------------------------------------

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, abstract_state: Any = None, step: Optional[int] = None) -> Any:
        """Restore a checkpoint.

        Args:
          abstract_state: pytree of jax.ShapeDtypeStruct (optionally with
            .sharding set) describing the target layout; None restores
            host-side numpy arrays.
          step: which step to load (default: latest).
        """
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found under {self.directory}")
        if abstract_state is None:
            return self._mgr.restore(step)
        return self._mgr.restore(step, args=ocp.args.StandardRestore(abstract_state))

    # -- lifecycle ----------------------------------------------------------

    def wait(self):
        """Block until queued async saves hit disk."""
        self._mgr.wait_until_finished()

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self):
        # idempotent: context-manager exit + an explicit finish()/close()
        # (two owners sharing one manager) must not double-close orbax
        if self._closed:
            return
        self._closed = True
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# --- crash-consistent checkpoints (reliability layer) ------------------------
#
# The orbax wrapper above trusts its files; a preempted VM can leave a torn
# write behind that only surfaces as a deserialization error days later, at
# the worst possible time (the resume after the crash). The verified manager
# makes corruption a HANDLED input instead:
#
#   * atomic writes — serialize to a tmp file in the same directory, fsync,
#     then `os.replace` (POSIX-atomic), so a reader never sees a partial
#     step file under its final name;
#   * per-step sha256 manifest — written (atomically) AFTER the data file;
#     a step without a matching manifest hash is treated as absent;
#   * fallback restore — `restore()` walks verified steps newest-first, so
#     a truncated/corrupted newest step degrades to the previous verified
#     one (with a printed warning) instead of a crash;
#   * bounded retention — `max_to_keep` pruning that NEVER deletes the
#     newest verified step, even when newer (unverified) files exist.
#
# Fault injection: `fault_hook(step, state_path, manifest_path)` runs after
# each completed write — reliability.FaultInjector.checkpoint_hook() damages
# the files there exactly the way a crash mid-write would
# (tests/test_chaos.py asserts the fallback).


_STATE_FMT = "step_{:08d}.npz"
_MANIFEST_SUFFIX = ".manifest.json"


def _host_tree(state):
    """Host-side numpy copy of a (possibly multi-process-sharded) state.

    Single-process (and fully-addressable arrays) this is plain
    device_get. On a pod, a leaf sharded across processes (TP params, DP
    opt state) is not fetchable locally — `compat.process_allgather`
    materializes the GLOBAL value on every host. COLLECTIVE: every
    process must call this in lockstep (the saver does, before gating
    the actual write to process 0)."""

    def host_leaf(x):
        if hasattr(x, "is_fully_addressable"):
            if x.is_fully_addressable or getattr(x, "is_fully_replicated", False):
                return np.asarray(jax.device_get(x))
            return np.asarray(compat.process_allgather(x, tiled=True))
        return np.asarray(jax.device_get(x))

    return jax.tree_util.tree_map(host_leaf, state)


def _leaf_paths(tree):
    """(json-able path, host numpy leaf) pairs in flatten order."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        segs = []
        for p in path:
            if isinstance(p, jax.tree_util.DictKey):
                segs.append(["k", p.key])
            elif isinstance(p, jax.tree_util.SequenceKey):
                segs.append(["i", p.idx])
            elif isinstance(p, jax.tree_util.GetAttrKey):
                segs.append(["a", p.name])
            else:
                raise TypeError(
                    f"unsupported pytree path entry {p!r} — the verified "
                    "manager serializes dict/tuple/list states"
                )
        out.append((segs, leaf))
    return out


def _rebuild_from_paths(items):
    """Nested dict/list pytree from (path segments, array) pairs — the
    no-template restore path. Sequence nodes come back as lists over the
    indices PRESENT in the paths (leaf-free subtrees like optax's
    EmptyState leave index gaps and are dropped from this host-side view);
    a template restore preserves the exact container types and structure."""
    root: dict = {}
    for segs, arr in items:
        node = root
        for kind, key in segs[:-1]:
            node = node.setdefault((kind, key), {})
        kind, key = segs[-1]
        node[(kind, key)] = arr

    def materialize(node):
        if not isinstance(node, dict):
            return node
        if node and all(k[0] == "i" for k in node):
            return [materialize(node[k]) for k in sorted(node)]
        return {key: materialize(v) for (kind, key), v in node.items()}

    return materialize(root)


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _pack_leaf(arr: np.ndarray):
    """(storable array, meta) for one leaf. npz round-trips native dtypes
    but silently degrades extension dtypes (ml_dtypes bfloat16 and friends,
    numpy kind 'V') to raw void — a bf16 checkpoint would then verify on
    save and crash on restore. Such leaves are stored as flat uint8 with
    the true dtype/shape in the manifest."""
    if arr.dtype.kind == "V":
        return np.frombuffer(arr.tobytes(), np.uint8), {
            "dtype": str(arr.dtype), "shape": list(arr.shape), "packed": True,
        }
    return arr, {"dtype": str(arr.dtype), "shape": list(arr.shape),
                 "packed": False}


def _unpack_leaf(arr: np.ndarray, meta) -> np.ndarray:
    if not meta or not meta.get("packed"):
        return arr
    try:
        dtype = np.dtype(meta["dtype"])
    except TypeError:
        import ml_dtypes  # registers bfloat16/float8 dtype names

        dtype = np.dtype(getattr(ml_dtypes, meta["dtype"]))
    return np.frombuffer(arr.tobytes(), dtype).reshape(meta["shape"])


class VerifiedCheckpointManager:
    """Crash-consistent, content-verified checkpoints (API-compatible with
    `CheckpointManager`, so `restore_or_init` / `open_or_init` / `finish`
    and `run_resilient` drive either).

    Synchronous by design: the write must be durable before the train loop
    advances past a preemption poll point, and the npz serialization the
    sizes this repo trains at is milliseconds — async would only reopen
    the torn-write window this class exists to close.

    Multi-host: every process calls save()/restore() in lockstep (SPMD).
    save() materializes the host copy collectively (cross-process leaves
    allgather), PROCESS 0 alone writes and prunes, and a cross-process
    barrier fences the write; restore() reads from the (shared —
    contract) directory on every process and cross-checks the chosen
    step's sha256 against process 0 before loading, so a divergent
    directory fails loudly instead of training from inconsistent states.
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 save_interval_steps: int = 1,
                 fault_hook: Optional[Callable[[int, str, str], None]] = None):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = max_to_keep
        self.save_interval_steps = max(1, save_interval_steps)
        self._fault_hook = fault_hook
        self._closed = False
        # steps whose sha256 already checked out: checkpoint files are
        # immutable once their manifest matches, so re-hashing multi-GB
        # states on every save/latest_step would make checkpoint cadence
        # cost grow with retention
        self._verified = set()

    # -- paths / verification ------------------------------------------------

    def _state_path(self, step: int) -> str:
        return os.path.join(self.directory, _STATE_FMT.format(step))

    def _manifest_path(self, step: int) -> str:
        return self._state_path(step) + _MANIFEST_SUFFIX

    def all_steps(self):
        """Every step with a state file on disk, verified or not, ascending."""
        steps = []
        for p in glob.glob(os.path.join(self.directory, "step_*.npz")):
            name = os.path.basename(p)
            try:
                steps.append(int(name[len("step_"):-len(".npz")]))
            except ValueError:
                continue
        return sorted(steps)

    def verify(self, step: int) -> bool:
        """True when the step's manifest exists and its sha256 matches the
        data file — the crash-consistency check restore trusts. The full
        hash runs once per step per manager (cached; existence is still
        re-checked so external deletion is noticed)."""
        state_path, manifest_path = self._state_path(step), self._manifest_path(step)
        if not (os.path.exists(state_path) and os.path.exists(manifest_path)):
            self._verified.discard(step)
            return False
        if step in self._verified:
            return True
        try:
            with open(manifest_path) as f:
                manifest = json.load(f)
        except (OSError, json.JSONDecodeError):
            return False
        ok = (
            manifest.get("step") == step
            and manifest.get("sha256") == _sha256_file(state_path)
        )
        if ok:
            self._verified.add(step)
        return ok

    def verified_steps(self):
        return [s for s in self.all_steps() if self.verify(s)]

    def latest_step(self) -> Optional[int]:
        """Newest VERIFIED step (corrupt/torn steps are invisible here)."""
        steps = self.verified_steps()
        return steps[-1] if steps else None

    # -- save ---------------------------------------------------------------

    def save(self, state: Any, step: Optional[int] = None, force: bool = False) -> bool:
        if self._closed:
            raise RuntimeError("save() on a closed VerifiedCheckpointManager")
        if step is None:
            step = int(np.asarray(_host_tree(state["step"])))
        if not force and step % self.save_interval_steps != 0:
            return False
        # COLLECTIVE on a pod: PROCESS 0 materializes the host copy and
        # writes; the others only join the allgathers that
        # cross-process-sharded leaves need (replicated leaves cost them
        # nothing — no point device_getting GBs to discard), and the
        # barrier below keeps any process from racing ahead to a restore
        # (or exit) before the files are durable. Multi-host contract:
        # `self.directory` is one SHARED filesystem. Leaf order is the
        # flatten order on every process, so the collectives line up.
        if jax.process_count() > 1 and jax.process_index() != 0:
            for leaf in jax.tree_util.tree_leaves(state):
                if (hasattr(leaf, "is_fully_addressable")
                        and not leaf.is_fully_addressable
                        and not getattr(leaf, "is_fully_replicated", False)):
                    compat.process_allgather(leaf, tiled=True)
            compat.sync_global_devices(f"af2:ckpt:save:{step}")
            return True
        items = _leaf_paths(_host_tree(state))
        if jax.process_index() == 0:
            arrays, leaf_meta = {}, []
            for i, (_, leaf) in enumerate(items):
                packed, meta = _pack_leaf(np.asarray(leaf))
                arrays[f"leaf_{i:05d}"] = packed
                leaf_meta.append(meta)

            state_path = self._state_path(step)
            tmp = state_path + ".tmp"
            with open(tmp, "wb") as f:
                np.savez(f, **arrays)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, state_path)

            manifest = {
                "step": step,
                "sha256": _sha256_file(state_path),
                "leaves": len(items),
                "paths": [segs for segs, _ in items],
                "leaf_meta": leaf_meta,
            }
            manifest_path = self._manifest_path(step)
            tmp = manifest_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(manifest, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, manifest_path)

            if self._fault_hook is not None:
                self._fault_hook(step, state_path, manifest_path)
            self._prune()
        compat.sync_global_devices(f"af2:ckpt:save:{step}")
        return True

    def _prune(self):
        """Drop oldest steps beyond max_to_keep — but the newest verified
        step is sacrosanct: with the newest file torn, it is the only
        restore target, and retention must never widen a corruption event
        into total loss."""
        if self.max_to_keep is None or self.max_to_keep < 1:
            return
        steps = self.all_steps()
        excess = len(steps) - self.max_to_keep
        if excess <= 0:
            return
        newest_verified = self.latest_step()
        for step in steps:
            if excess <= 0:
                break
            if step == newest_verified:
                continue
            for p in (self._state_path(step), self._manifest_path(step)):
                if os.path.exists(p):
                    os.unlink(p)
            self._verified.discard(step)
            excess -= 1

    # -- restore ------------------------------------------------------------

    def _load(self, step: int, abstract_state: Any):
        with open(self._manifest_path(step)) as f:
            manifest = json.load(f)
        meta = manifest.get("leaf_meta") or [None] * manifest["leaves"]
        with np.load(self._state_path(step)) as data:
            arrays = [
                _unpack_leaf(data[f"leaf_{i:05d}"], meta[i])
                for i in range(manifest["leaves"])
            ]
        if abstract_state is None:
            return _rebuild_from_paths(zip(manifest["paths"], arrays))
        stored = {json.dumps(segs): arr
                  for segs, arr in zip(manifest["paths"], arrays)}
        out = []
        for segs, template in _leaf_paths(abstract_state):
            key = json.dumps(segs)
            if key not in stored:
                raise KeyError(
                    f"checkpoint step {step} has no leaf at {key} — template "
                    "and checkpoint layouts diverged"
                )
            arr = stored[key]
            sharding = getattr(template, "sharding", None)
            # make_global_array_from_host, not device_put: on a pod the
            # restored bytes are identical on every process (verified +
            # broadcast-checked), so each process feeds its own shards —
            # a cross-process device_put broadcast is wasted wire (and
            # trips gloo on CPU pods)
            out.append(
                compat.make_global_array_from_host(arr, sharding)
                if sharding is not None else jax.numpy.asarray(arr)
            )
        leaves, treedef = jax.tree_util.tree_flatten(abstract_state)
        assert len(leaves) == len(out)
        return jax.tree_util.tree_unflatten(treedef, out)

    def _assert_consistent_across_processes(self, step: int) -> None:
        """Pod restore sanity: every process must be about to load the
        SAME verified bytes. Process 0's (step, sha256) broadcasts to
        all; a mismatch means the processes see divergent checkpoint
        directories (non-shared filesystem, torn replication) — restore
        proceeding would silently train from inconsistent states, so it
        raises instead."""
        if jax.process_count() <= 1:
            return
        with open(self._manifest_path(step)) as f:
            sha = json.load(f).get("sha256", "")
        local = np.frombuffer(f"{step:08d}:{sha}".encode(), np.uint8)
        ref = np.asarray(compat.broadcast_one_to_all(local))
        if not np.array_equal(ref, local):
            raise RuntimeError(
                f"process {jax.process_index()} would restore step {step} "
                f"sha {sha[:12]}..., but process 0 sees different bytes — "
                f"the checkpoint directory {self.directory} is not "
                "consistent across processes (multi-host checkpointing "
                "requires one shared filesystem)"
            )

    def restore(self, abstract_state: Any = None, step: Optional[int] = None) -> Any:
        """Restore `step` (must verify) or, by default, the newest step that
        PASSES verification — falling back past corrupt/truncated newer
        steps with a printed warning per skipped step. Multi-process,
        the chosen step is cross-checked against process 0 before any
        bytes load (broadcast-consistent restore)."""
        if step is not None:
            if not self.verify(step):
                raise FileNotFoundError(
                    f"checkpoint step {step} in {self.directory} is missing "
                    "or failed sha256 verification"
                )
            self._assert_consistent_across_processes(step)
            return self._load(step, abstract_state)
        candidates = self.all_steps()
        if not candidates:
            raise FileNotFoundError(f"no checkpoint found under {self.directory}")
        for s in reversed(candidates):
            if self.verify(s):
                self._assert_consistent_across_processes(s)
                return self._load(s, abstract_state)
            print(f"warning: checkpoint step {s} in {self.directory} failed "
                  "verification (torn write or corruption) — falling back")
        raise FileNotFoundError(
            f"no checkpoint under {self.directory} passes verification "
            f"(steps on disk: {candidates})"
        )

    # -- lifecycle ----------------------------------------------------------

    @property
    def closed(self) -> bool:
        return self._closed

    def wait(self):
        """Writes are synchronous; nothing to drain."""

    def close(self):
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def abstract_like(state: Any, shardings: Any = None):
    """ShapeDtypeStruct skeleton of `state` for sharded restore.

    `shardings`: matching pytree of jax.sharding.Sharding (e.g. from
    parallel.state_shardings). When None, shardings are DERIVED from the
    state's own leaves (live jax.Arrays carry `.sharding`; host numpy
    leaves restore placement-free) — so restoring "like" a live sharded
    state round-trips its layout without the caller threading the
    shardings tree separately (the crash-recovery path in
    `run_resilient` restores against the in-memory good state, which on
    a pod is already globally sharded)."""
    shapes = jax.eval_shape(lambda s: s, state)
    if shardings is None:
        def derived(leaf, sds):
            sh = getattr(leaf, "sharding", None)
            if sh is None:
                return sds
            return jax.ShapeDtypeStruct(sds.shape, sds.dtype, sharding=sh)

        return jax.tree_util.tree_map(derived, state, shapes)
    return jax.tree_util.tree_map(
        lambda sds, sh: jax.ShapeDtypeStruct(sds.shape, sds.dtype, sharding=sh),
        shapes,
        shardings,
    )


def restore_or_init(mgr: CheckpointManager, init_fn, *init_args, shardings: Any = None):
    """Resume-from-latest or cold-start: the standard top-of-loop idiom.

    Returns (state, resumed: bool).
    """
    step = mgr.latest_step()
    if step is None:
        return init_fn(*init_args), False
    # shapes only — no param materialization on the resume path
    template = jax.eval_shape(lambda: init_fn(*init_args))
    return mgr.restore(abstract_like(template, shardings), step=step), True


def open_or_init(
    ckpt_dir: Optional[str],
    init_fn,
    *init_args,
    save_every: int = 1,
    shardings: Any = None,
    verify: bool = False,
    fault_hook=None,
):
    """Entry-script idiom shared by train_pre.py / train_end2end.py.

    Returns (mgr, state, resumed); mgr is None when ckpt_dir is None.
    Interval gating is delegated to the manager's save_interval_steps —
    call `mgr.save(state)` every step and it decides.

    verify=True (the --ckpt-verify flag) selects the crash-consistent
    `VerifiedCheckpointManager` (atomic writes + sha256 manifests +
    fallback restore); `fault_hook` is its chaos-injection seam and
    requires verify=True.
    """
    if ckpt_dir is None:
        return None, init_fn(*init_args), False
    if verify:
        mgr = VerifiedCheckpointManager(
            ckpt_dir, save_interval_steps=max(1, save_every),
            fault_hook=fault_hook,
        )
    else:
        if fault_hook is not None:
            raise ValueError(
                "checkpoint fault injection needs the verified manager — "
                "pass verify=True (--ckpt-verify)"
            )
        mgr = CheckpointManager(ckpt_dir, save_interval_steps=max(1, save_every))
    state, resumed = restore_or_init(mgr, init_fn, *init_args, shardings=shardings)
    return mgr, state, resumed


def restore_params_for_inference(ckpt_dir: Optional[str], init_fn, *init_args,
                                 cold_params_fn=None):
    """Inference-entry idiom shared by predict.py and serve.py: restore the
    latest checkpoint read-only (no manager kept open, nothing to flush),
    fall back to fresh init with a warning.

    `init_fn(*init_args)` is the TRAIN-state init matching the checkpoint
    layout; on the restore path it is only eval_shape'd (restore_or_init),
    so optimizer moments are never materialized. `cold_params_fn()` is the
    params-only init for the no-checkpoint path — without it the cold
    start would materialize (and immediately discard) the full opt state,
    ~2x parameter memory under Adam.

    Returns (params, step, resumed) — step is 0 when cold-started. Callers
    use `f"{ckpt_dir}@step{step}"` as the result-cache fingerprint.
    """
    def cold_params():
        if cold_params_fn is not None:
            return cold_params_fn()
        return init_fn(*init_args)["params"]

    if ckpt_dir is None:
        print("no --ckpt-dir: using randomly initialized params")
        return cold_params(), 0, False
    with CheckpointManager(ckpt_dir) as mgr:
        # probe before delegating to restore_or_init: its cold branch
        # materializes the full train state, which would defeat
        # cold_params_fn on an empty/not-yet-written checkpoint dir
        if mgr.latest_step() is None:
            print(f"warning: no checkpoint in {ckpt_dir}; random params")
            return cold_params(), 0, False
        state, _ = restore_or_init(mgr, init_fn, *init_args)
    step = int(np.asarray(jax.device_get(state["step"])))
    print(f"restored step-{step} params from {ckpt_dir}")
    return state["params"], step, True


def finish(mgr: Optional["CheckpointManager"], state: Any):
    """Final flush at end of training: save the last step if the periodic
    cadence missed it, then drain and close. A no-op on an
    already-closed manager (a preemption path that checkpointed and
    closed, followed by the entry script's unconditional finish, must not
    crash the clean exit)."""
    if mgr is None or getattr(mgr, "closed", False):
        return
    step = int(np.asarray(jax.device_get(state["step"])))
    if mgr.latest_step() != step:
        mgr.save(state, force=True)
    mgr.close()
