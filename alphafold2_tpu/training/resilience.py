"""Failure detection and elastic recovery for training loops.

The reference has nothing here — "not even try/except around training"
(SURVEY.md §5); a crashed or NaN-poisoned run simply dies. This module is
the recovery layer our checkpoint subsystem makes possible:

  * `StepGuard` — NaN/Inf watchdog over step metrics: poisoned steps are
    detected on the host (one scalar sync that the metrics logger pays
    anyway), the update is rolled back to the last good state, and
    training continues; repeated poisoning within a window aborts with a
    clear error instead of silently training on garbage.
  * `run_resilient` — a supervisor loop: runs the jitted step, checkpoints
    on cadence, and on ANY exception (device OOM, preemption-style
    interrupts, data errors) restores from the latest checkpoint and
    resumes, up to `max_restarts`. This is single-process elastic recovery
    — the multi-host story composes the same primitive with
    `jax.distributed` restart semantics.
"""

from __future__ import annotations

import math
from typing import Callable, Optional

import jax
import numpy as np

from alphafold2_tpu.telemetry import NULL_TRACER


class BadStepError(RuntimeError):
    """Raised when non-finite steps persist beyond the tolerated window."""


def _assert_live(state, what: str):
    """Fail loudly if a kept rollback state was invalidated by donation.

    StepGuard keeps a no-copy reference to the pre-step state; if the step
    function donates its state argument (make_sharded_train_step
    donate_state=True), those buffers are deleted after the step and a
    rollback would hand back dead arrays. Catch that here with a clear
    message instead of a deep XLA 'buffer has been deleted' error.
    """
    for leaf in jax.tree_util.tree_leaves(state):
        if getattr(leaf, "is_deleted", lambda: False)():
            raise RuntimeError(
                f"{what} was invalidated by buffer donation — run the guarded "
                "loop with a non-donating step (donate_state=False), or "
                "snapshot states before stepping"
            )


class StepGuard:
    """Rolls back non-finite steps; aborts when they persist.

    Keeps a reference to the last known-good state (a no-copy pytree
    reference — jax arrays are immutable, so 'keeping' it is free). This
    requires a NON-donating step function: donation would delete the kept
    buffers (checked on rollback with a clear error).

    A step is bad when the loss OR the gradient norm is non-finite: clipped
    inf gradients can leave a finite loss while the params are already
    poisoned, so loss alone under-detects (the metrics dict from
    make_train_step always carries "grad_norm").
    """

    def __init__(self, state, max_consecutive_bad: int = 3):
        self.good_state = state
        self.max_consecutive_bad = max_consecutive_bad
        self.bad_streak = 0
        self.bad_total = 0

    def check(self, new_state, metrics) -> tuple:
        """Returns (state_to_continue_from, step_was_good)."""
        loss = float(np.asarray(jax.device_get(metrics["loss"])))
        good = math.isfinite(loss)
        if good and "grad_norm" in metrics:
            good = math.isfinite(float(np.asarray(jax.device_get(metrics["grad_norm"]))))
        if good:
            self.good_state = new_state
            self.bad_streak = 0
            return new_state, True
        self.bad_streak += 1
        self.bad_total += 1
        if self.bad_streak >= self.max_consecutive_bad:
            raise BadStepError(
                f"{self.bad_streak} consecutive non-finite losses; "
                "aborting instead of training on garbage"
            )
        _assert_live(self.good_state, "StepGuard rollback state")
        return self.good_state, False


def run_resilient(
    step_fn: Callable,
    state,
    batches,
    *,
    steps: int,
    make_rng: Callable[[int], object],
    mgr=None,
    on_metrics: Optional[Callable[[int, dict], None]] = None,
    max_restarts: int = 3,
    max_consecutive_bad: int = 3,
    logger=None,
    preemption=None,
    tracer=None,
    telemetry=None,
):
    """Supervised training loop with rollback and checkpoint-restore retry.

    Args:
      step_fn: jitted (state, batch, rng) -> (state, metrics).
      state: initial TrainState (its "step" entry drives numbering).
      batches: batch iterator (consumed once per attempted step) OR a
        step-indexed callable `fetch(step) -> batch` (e.g.
        data.synthetic_microbatch_fn / a callable ResilientBatches). The
        callable form makes recovery REPLAY-EXACT: a restarted or
        rolled-back step refetches the identical batch, so a faulted run
        reconverges bit-exact with a fault-free one (the chaos-suite
        invariant). The iterator form keeps the old semantics — a retried
        step consumes the next batch.
      steps: number of steps to run from the CURRENT state step.
      make_rng: step index -> PRNG key (use jax.random.fold_in for
        resume-stable schedules).
      mgr: optional CheckpointManager / VerifiedCheckpointManager; saves
        ride its save_interval_steps cadence and recovery restores from it.
      on_metrics: callback(step, metrics) for logging.
      max_restarts: consecutive exception-recovery budget. When exceeded,
        the abort carries the WHOLE restart cause chain in its message —
        "what killed the run" must not require scrolling back through days
        of logs.
      logger: optional utils.MetricsLogger; every restart is recorded as a
        structured `restart` event ((exception type, step, restart count))
        and the run ends with a `resilience_summary` event.
      preemption: optional reliability.PreemptionHandler; polled at each
        step boundary. On SIGTERM the loop force-saves the current state,
        drains the manager, and raises `Preempted` — the next run resumes
        bit-exact from that checkpoint.
      tracer: optional telemetry.Tracer; each step becomes four phase
        spans (train.fetch / train.step / train.metrics_fetch /
        train.checkpoint) and every recovery episode a train.restore
        span. NOTE on the split: the jitted step dispatches
        asynchronously, so train.step measures dispatch and
        train.metrics_fetch absorbs the device execution it waits on —
        together they are the true step wall time.
      telemetry: optional telemetry.TrainTelemetry; the goodput ledger
        accounts every phase into its wall-clock buckets (fetch ->
        data_fetch, step dispatch + metrics sync -> compile on the first
        step then step, checkpoint/restore/preempt likewise) and
        `step_complete` drives the per-step histograms, stall detection,
        and — on a pod — the COLLECTIVE federation tick (safe here
        precisely because every process runs this loop in lockstep).

    Returns the final state.
    """
    from alphafold2_tpu.telemetry.goodput import NULL_TRAIN_TELEMETRY

    tracer = tracer if tracer is not None else NULL_TRACER
    telemetry = telemetry if telemetry is not None else NULL_TRAIN_TELEMETRY
    start = int(np.asarray(jax.device_get(state["step"])))
    target = start + steps
    restarts = 0
    causes = []  # (step, exception type, message head) per restart, lifetime
    guard = StepGuard(state, max_consecutive_bad=max_consecutive_bad)
    # a callable source is used step-indexed even when it also iterates
    # (ResilientBatches is both): replay-exactness must win whenever the
    # source can provide it
    step_indexed = callable(batches)

    def fetch(step):
        try:
            return batches(step) if step_indexed else next(batches)
        except StopIteration:
            raise RuntimeError(
                f"data exhausted at step {step} (before target {target}); "
                "not a recoverable fault"
            ) from None

    def record_restart(step, exc, where):
        causes.append((step, type(exc).__name__, str(exc).splitlines()[0][:200]))
        if logger is not None:
            logger.event(step, "restart", error=type(exc).__name__,
                         message=str(exc)[:500], restart=restarts,
                         max_restarts=max_restarts, restored_from=where)

    while True:
        step = int(np.asarray(jax.device_get(state["step"])))
        if preemption is not None and preemption.check():
            from alphafold2_tpu.reliability.preemption import Preempted

            if mgr is not None:
                with tracer.span("train.preempt_checkpoint",
                                 cat="reliability", step=step), \
                        telemetry.account("preempt"):
                    mgr.save(state, force=True)
                    mgr.wait()
                    mgr.close()
            if logger is not None:
                logger.event(step, "preempted", signum=preemption.signum,
                             checkpointed=mgr is not None)
            raise Preempted(step, checkpointed=mgr is not None)
        if step >= target:
            break
        try:
            with tracer.span("train.fetch", cat="train", step=step), \
                    telemetry.account("data_fetch"):
                batch = fetch(step)
            # the first step's dispatch blocks through trace+compile, so
            # its wall time books into the ledger's compile bucket; the
            # metrics sync is the same bucket — dispatch + sync together
            # are the true step wall (the span-taxonomy note below)
            step_bucket = telemetry.step_bucket()
            with tracer.span("train.step", cat="train", step=step), \
                    telemetry.account(step_bucket):
                new_state, metrics = step_fn(state, batch, make_rng(step))
            # the guard's finiteness check is the step's one device sync
            with tracer.span("train.metrics_fetch", cat="train",
                             step=step), telemetry.account(step_bucket):
                state, ok = guard.check(new_state, metrics)
            if ok:
                # a successful step clears the restart budget: the limit is
                # on CONSECUTIVE failures, not failures over the run's life
                restarts = 0
                telemetry.step_complete(step)
                if on_metrics is not None:
                    on_metrics(step, metrics)
                if mgr is not None:
                    with tracer.span("train.checkpoint", cat="train",
                                     step=step), \
                            telemetry.account("checkpoint"):
                        mgr.save(state)
            else:
                print(f"step {step}: non-finite loss — rolled back, retrying")
        except (BadStepError, KeyboardInterrupt):
            raise
        except Exception as e:  # crash-recovery path
            restarts += 1
            if restarts > max_restarts:
                record_restart(step, e, "ABORT (budget exhausted)")
                chain = "; ".join(
                    f"{name}({msg!r}) at step {s}" for s, name, msg in causes
                )
                raise RuntimeError(
                    f"restart budget exhausted (max_restarts="
                    f"{max_restarts}) at step {step}; cause chain: {chain}"
                ) from e
            # the whole recovery episode is one reliability span: what
            # killed the step, where the state came back from, how long
            # the restore cost
            with tracer.span("train.restore", cat="reliability", step=step,
                             cause=type(e).__name__) as rsp, \
                    telemetry.account("restore"):
                if mgr is not None and mgr.latest_step() is not None:
                    from alphafold2_tpu.training.checkpoint import abstract_like

                    state = mgr.restore(abstract_like(guard.good_state))
                    where = f"checkpoint step {int(np.asarray(state['step']))}"
                else:
                    _assert_live(guard.good_state, "in-memory recovery state")
                    state = guard.good_state
                    where = "last good in-memory state"
                rsp.set("restored_from", where)
            guard.good_state = state
            guard.bad_streak = 0  # restored state is clean; stale NaN counts
            # from before the crash must not count against it
            record_restart(step, e, where)
            print(
                f"step {step}: {type(e).__name__}: {e} — "
                f"restart {restarts}/{max_restarts} from {where}"
            )
    if logger is not None:
        logger.event(target, "resilience_summary",
                     restarts_total=len(causes),
                     rollbacks_total=guard.bad_total,
                     causes=[{"step": s, "error": n, "message": m}
                             for s, n, m in causes])
    if mgr is not None:
        from alphafold2_tpu.training.checkpoint import finish

        finish(mgr, state)
    return state


# --- shared trainer CLI surface ---------------------------------------------


def add_resilience_args(ap):
    """The recovery/chaos argparse block shared by train_pre.py and
    train_end2end.py — the flags that let the chaos harness drive the REAL
    entrypoints instead of unit fixtures."""
    ap.add_argument("--max-restarts", type=int, default=0,
                    help="run under the run_resilient supervisor with this "
                         "consecutive crash-restart budget (0 = plain loop; "
                         "the supervisor needs a non-donating step, ~2x live "
                         "state footprint)")
    ap.add_argument("--ckpt-verify", action="store_true",
                    help="crash-consistent checkpoints: atomic tmp-then-"
                         "replace writes + per-step sha256 manifest; restore "
                         "falls back past corrupt/truncated steps to the "
                         "newest verified one")
    ap.add_argument("--fault-plan", default=None, metavar="PATH",
                    help="JSON fault schedule (reliability.FaultPlan) "
                         "injected into the run's step/data/checkpoint hook "
                         "points; implies the resilient loop")


def resilient_mode(args) -> bool:
    """True when the trainer should run under the run_resilient supervisor
    (either flag opts in; a fault plan without a restart budget gets a
    default budget of 3 so scheduled crashes are survivable)."""
    return args.max_restarts > 0 or args.fault_plan is not None


def chaos_from_args(args):
    """(injector, ckpt_fault_hook, effective_max_restarts) from the shared
    resilience flags. The checkpoint hook only exists under --ckpt-verify
    (the orbax manager has no injection seam); a plan that schedules
    ckpt_corrupt without it gets a loud warning, not silence."""
    injector, ckpt_hook = None, None
    if args.fault_plan is not None:
        from alphafold2_tpu.reliability import FaultPlan

        injector = FaultPlan.from_file(args.fault_plan).injector()
        has_ckpt_faults = any(
            f.kind == "ckpt_corrupt" for f in injector.plan.faults
        )
        if args.ckpt_verify:
            ckpt_hook = injector.checkpoint_hook()
        elif has_ckpt_faults:
            print("warning: --fault-plan schedules ckpt_corrupt but "
                  "--ckpt-verify is off; checkpoint faults will NOT fire")
    max_restarts = args.max_restarts or (3 if args.fault_plan else 0)
    return injector, ckpt_hook, max_restarts
