"""Failure detection and elastic recovery for training loops.

The reference has nothing here — "not even try/except around training"
(SURVEY.md §5); a crashed or NaN-poisoned run simply dies. This module is
the recovery layer our checkpoint subsystem makes possible:

  * `StepGuard` — NaN/Inf watchdog over step metrics: poisoned steps are
    detected on the host (one scalar sync that the metrics logger pays
    anyway), the update is rolled back to the last good state, and
    training continues; repeated poisoning within a window aborts with a
    clear error instead of silently training on garbage.
  * `run_resilient` — a supervisor loop: runs the jitted step, checkpoints
    on cadence, and on ANY exception (device OOM, preemption-style
    interrupts, data errors) restores from the latest checkpoint and
    resumes, up to `max_restarts`. This is single-process elastic recovery
    — the multi-host story composes the same primitive with
    `jax.distributed` restart semantics.
"""

from __future__ import annotations

import math
from typing import Callable, Iterator, Optional

import jax
import numpy as np


class BadStepError(RuntimeError):
    """Raised when non-finite steps persist beyond the tolerated window."""


def _assert_live(state, what: str):
    """Fail loudly if a kept rollback state was invalidated by donation.

    StepGuard keeps a no-copy reference to the pre-step state; if the step
    function donates its state argument (make_sharded_train_step
    donate_state=True), those buffers are deleted after the step and a
    rollback would hand back dead arrays. Catch that here with a clear
    message instead of a deep XLA 'buffer has been deleted' error.
    """
    for leaf in jax.tree_util.tree_leaves(state):
        if getattr(leaf, "is_deleted", lambda: False)():
            raise RuntimeError(
                f"{what} was invalidated by buffer donation — run the guarded "
                "loop with a non-donating step (donate_state=False), or "
                "snapshot states before stepping"
            )


class StepGuard:
    """Rolls back non-finite steps; aborts when they persist.

    Keeps a reference to the last known-good state (a no-copy pytree
    reference — jax arrays are immutable, so 'keeping' it is free). This
    requires a NON-donating step function: donation would delete the kept
    buffers (checked on rollback with a clear error).

    A step is bad when the loss OR the gradient norm is non-finite: clipped
    inf gradients can leave a finite loss while the params are already
    poisoned, so loss alone under-detects (the metrics dict from
    make_train_step always carries "grad_norm").
    """

    def __init__(self, state, max_consecutive_bad: int = 3):
        self.good_state = state
        self.max_consecutive_bad = max_consecutive_bad
        self.bad_streak = 0
        self.bad_total = 0

    def check(self, new_state, metrics) -> tuple:
        """Returns (state_to_continue_from, step_was_good)."""
        loss = float(np.asarray(jax.device_get(metrics["loss"])))
        good = math.isfinite(loss)
        if good and "grad_norm" in metrics:
            good = math.isfinite(float(np.asarray(jax.device_get(metrics["grad_norm"]))))
        if good:
            self.good_state = new_state
            self.bad_streak = 0
            return new_state, True
        self.bad_streak += 1
        self.bad_total += 1
        if self.bad_streak >= self.max_consecutive_bad:
            raise BadStepError(
                f"{self.bad_streak} consecutive non-finite losses; "
                "aborting instead of training on garbage"
            )
        _assert_live(self.good_state, "StepGuard rollback state")
        return self.good_state, False


def run_resilient(
    step_fn: Callable,
    state,
    batches: Iterator,
    *,
    steps: int,
    make_rng: Callable[[int], object],
    mgr=None,
    on_metrics: Optional[Callable[[int, dict], None]] = None,
    max_restarts: int = 3,
    max_consecutive_bad: int = 3,
):
    """Supervised training loop with rollback and checkpoint-restore retry.

    Args:
      step_fn: jitted (state, batch, rng) -> (state, metrics).
      state: initial TrainState (its "step" entry drives numbering).
      batches: batch iterator (consumed once per attempted step).
      steps: number of steps to run from the CURRENT state step.
      make_rng: step index -> PRNG key (use jax.random.fold_in for
        resume-stable schedules).
      mgr: optional CheckpointManager; saves ride its save_interval_steps
        cadence and recovery restores from it.
      on_metrics: callback(step, metrics) for logging.
      max_restarts: exception-recovery budget.

    Returns the final state.
    """
    start = int(np.asarray(jax.device_get(state["step"])))
    target = start + steps
    restarts = 0
    guard = StepGuard(state, max_consecutive_bad=max_consecutive_bad)

    while True:
        step = int(np.asarray(jax.device_get(state["step"])))
        if step >= target:
            break
        try:
            try:
                batch = next(batches)
            except StopIteration:
                raise RuntimeError(
                    f"data exhausted at step {step} (before target {target}); "
                    "not a recoverable fault"
                ) from None
            new_state, metrics = step_fn(state, batch, make_rng(step))
            state, ok = guard.check(new_state, metrics)
            if ok:
                # a successful step clears the restart budget: the limit is
                # on CONSECUTIVE failures, not failures over the run's life
                restarts = 0
                if on_metrics is not None:
                    on_metrics(step, metrics)
                if mgr is not None:
                    mgr.save(state)
            else:
                print(f"step {step}: non-finite loss — rolled back, retrying")
        except (BadStepError, KeyboardInterrupt):
            raise
        except Exception as e:  # crash-recovery path
            restarts += 1
            if restarts > max_restarts:
                raise
            if mgr is not None and mgr.latest_step() is not None:
                from alphafold2_tpu.training.checkpoint import abstract_like

                state = mgr.restore(abstract_like(guard.good_state))
                where = f"checkpoint step {int(np.asarray(state['step']))}"
            else:
                _assert_live(guard.good_state, "in-memory recovery state")
                state = guard.good_state
                where = "last good in-memory state"
            guard.good_state = state
            guard.bad_streak = 0  # restored state is clean; stale NaN counts
            # from before the crash must not count against it
            print(
                f"step {step}: {type(e).__name__}: {e} — "
                f"restart {restarts}/{max_restarts} from {where}"
            )
    if mgr is not None:
        from alphafold2_tpu.training.checkpoint import finish

        finish(mgr, state)
    return state
