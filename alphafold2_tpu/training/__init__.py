"""Training harness layer.

Replaces the reference's inlined script loops and empty launcher stubs
(reference train_pre.py, train_end2end.py, training_scripts/) with a
first-class subsystem: losses, an optax-based jitted train step with
scanned gradient accumulation, and a static-shape data pipeline.
"""

from alphafold2_tpu.training.losses import (
    IGNORE_INDEX,
    bucketed_distance_matrix,
    distogram_cross_entropy,
)
from alphafold2_tpu.training.harness import (
    add_train_args,
    tcfg_from_args,
    TrainConfig,
    distogram_loss_fn,
    make_optimizer,
    make_train_step,
    train_state_init,
    with_fault_injection,
)
from alphafold2_tpu.training.data import (
    DataConfig,
    ResilientBatches,
    assemble_global_batch,
    bucket_batches,
    bucketed_microbatches,
    per_process_microbatch_fn,
    process_shard,
    resilient_batches,
    shard_items,
    stack_microbatches,
    synthetic_batches,
    synthetic_microbatch_fn,
    synthetic_structure_batches,
    sidechainnet_batches,
    sidechainnet_structure_batches,
)
from alphafold2_tpu.training.e2e import (
    E2EConfig,
    e2e_loss_fn,
    make_e2e_loss_fn,
    e2e_train_state_init,
    predict_structure,
)
from alphafold2_tpu.training.presets import (
    north_star_e2e_config,
)
from alphafold2_tpu.training.segmented import (
    make_segmented_train_step,
)
from alphafold2_tpu.training.checkpoint import (
    CheckpointManager,
    VerifiedCheckpointManager,
    abstract_like,
    finish,
    open_or_init,
    restore_or_init,
    restore_params_for_inference,
)
from alphafold2_tpu.training.resilience import (
    BadStepError,
    StepGuard,
    add_resilience_args,
    chaos_from_args,
    resilient_mode,
    run_resilient,
)

__all__ = [
    "add_train_args",
    "tcfg_from_args",
    "BadStepError",
    "StepGuard",
    "add_resilience_args",
    "chaos_from_args",
    "resilient_mode",
    "run_resilient",
    "with_fault_injection",
    "CheckpointManager",
    "VerifiedCheckpointManager",
    "abstract_like",
    "finish",
    "open_or_init",
    "restore_or_init",
    "restore_params_for_inference",
    "E2EConfig",
    "e2e_loss_fn",
    "make_e2e_loss_fn",
    "e2e_train_state_init",
    "predict_structure",
    "synthetic_structure_batches",
    "IGNORE_INDEX",
    "bucketed_distance_matrix",
    "distogram_cross_entropy",
    "TrainConfig",
    "distogram_loss_fn",
    "make_optimizer",
    "make_train_step",
    "train_state_init",
    "DataConfig",
    "ResilientBatches",
    "bucket_batches",
    "bucketed_microbatches",
    "assemble_global_batch",
    "per_process_microbatch_fn",
    "process_shard",
    "resilient_batches",
    "shard_items",
    "stack_microbatches",
    "synthetic_batches",
    "synthetic_microbatch_fn",
    "sidechainnet_batches",
    "sidechainnet_structure_batches",
    "north_star_e2e_config",
    "make_segmented_train_step",
]
