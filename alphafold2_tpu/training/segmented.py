"""Multi-execution (segmented) end-to-end train step.

The tunneled single-chip environment kills XLA executions beyond ~60 s of
device time (PERF.md "Known environment limits"), which makes the
north-star depth-48 step (~96 s in one execution) unmeasurable as a
single program. This module runs the SAME optimizer step as
`make_train_step(e2e_loss_fn)` but as a chain of short device
executions, exploiting the reversible trunk's defining property: the
backward reconstructs each segment's input state from its output state,
so NO inter-segment activations are ever stored — the host passes one
(x1, x2, m1, m2) boundary between executions and nothing else.

Execution chain per optimizer step (each < ~depth/segments layer-costs):

  front      embeddings + template tower -> (x, m) and masks
  seg_fwd*K  reversible segments forward (state4 -> state4)
  tail       (z-streams mean) -> head -> distogram -> MDS -> sidechain ->
             refiner -> Kabsch loss, with value_and_grad wrt head params,
             refiner params, AND the trunk output state
  seg_bwd*K  reverse: reconstruct segment input state + propagate
             cotangents + per-segment trunk param grads
  front_bwd  vjp of front wrt model params (embeddings, template tower)
  opt        assemble grads, optax update (the same FIXED-ARITY chain as
             harness.make_optimizer), step += 1

Numerics are IDENTICAL to the monolithic step by construction: the same
`_layer_forward`/`_layer_backward` bodies run with the same global layer
indices (dropout keys are `fold_in(rng_trunk, layer)` — offset is passed
as a traced operand so equal-length segments share one compiled
executable), and the rng split chain mirrors
harness.make_train_step -> e2e_loss_fn -> predict_structure exactly.
Parity is pinned by tests/test_segmented.py.

Limitations: requires `cfg.reversible` and an MSA stream (the reversible
trunk's own requirements). The step is a HOST-LEVEL callable — it cannot
be jitted as a whole (that would defeat its purpose); each piece is.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from alphafold2_tpu.models import alphafold2_front, alphafold2_head
from alphafold2_tpu.models.reversible import (
    _layer_backward,
    _layer_forward,
    _num_layers,
    _op_rngs,
    uniform_flag_runs,
)
from alphafold2_tpu.training.e2e import E2EConfig, elongate, make_e2e_loss_fn
from alphafold2_tpu.training.harness import TrainConfig, make_optimizer


def plan_segments(layer_sparse, n_segments: int):
    """Split the depth into <= n_segments-per-uniform-run chunks.

    Segment boundaries must respect uniform sparse-flag runs (the scan
    body is specialized on the flag; the run computation is shared with
    the reversible trunk). Returns [(start, end, flag), ...].
    """
    depth = len(layer_sparse)
    target = max(1, -(-depth // max(1, n_segments)))  # ceil
    runs = uniform_flag_runs(layer_sparse)
    segments = []
    for run_start, run_end in runs:
        pos = run_start
        while pos < run_end:
            end = min(pos + target, run_end)
            segments.append((pos, end, layer_sparse[pos]))
            pos = end
    return segments


def _seg_fwd(cfg, sparse, seg_params, state4, x_mask, m_mask, rng, offset):
    def body(carry, inp):
        lp, li = inp
        return (
            _layer_forward(cfg, lp, carry, x_mask, m_mask,
                           _op_rngs(rng, li), sparse),
            None,
        )

    L = _num_layers(seg_params)
    carry, _ = jax.lax.scan(
        body, state4, (seg_params, offset + jnp.arange(L))
    )
    return carry


def _seg_bwd(cfg, sparse, seg_params, state4_end, cts4, x_mask, m_mask, rng,
             offset):
    def body(carry, inp):
        state, dstate = carry
        lp, li = inp
        state, dstate, dlp = _layer_backward(
            cfg, lp, state, dstate, x_mask, m_mask, _op_rngs(rng, li), sparse
        )
        return (state, dstate), dlp

    L = _num_layers(seg_params)
    (state4_start, cts4_start), dseg = jax.lax.scan(
        body, (state4_end, cts4), (seg_params, offset + jnp.arange(L)),
        reverse=True,
    )
    return state4_start, cts4_start, dseg


def _jit_static_sparse(fn):
    """jit with the leading `sparse` flag static (it selects the scan
    body); everything else traced — offsets included, so equal-length
    segments reuse one executable."""
    return jax.jit(fn, static_argnums=(0,))


def make_segmented_train_step(
    ecfg: E2EConfig, tcfg: TrainConfig, trunk_segments: int
):
    """Host-level train step running as a chain of short device executions.

    Same contract as `make_train_step(ecfg, tcfg, loss_fn=e2e_loss_fn)`:
    `step(state, batch, rng) -> (new_state, {"loss", "grad_norm"})`, with
    `batch` carrying the leading (grad_accum) microbatch axis. The
    returned state pytree is structurally identical (checkpoint compat).
    """
    cfg = ecfg.model
    if not cfg.reversible:
        raise ValueError("the segmented step requires cfg.reversible=True "
                         "(segment backward IS reversible reconstruction)")
    segments = plan_segments(cfg.layer_sparse, trunk_segments)
    opt = make_optimizer(tcfg)

    # --- jitted pieces (compiled once per shape/static combination) -------

    @jax.jit
    def front_fwd(model_params, seq3, msa, mask3, msa_mask, embedds,
                  rng_model):
        return alphafold2_front(
            model_params, cfg, seq3, msa, mask=mask3, msa_mask=msa_mask,
            embedds=embedds, rng=rng_model,
        )

    # sparse flag is static (different scan body); offset is traced so all
    # equal-length segments of a run share ONE executable
    @_jit_static_sparse
    def seg_fwd(sparse, seg_params, state4, x_mask, m_mask, rng, offset):
        return _seg_fwd(cfg, sparse, seg_params, state4, x_mask, m_mask,
                        rng, offset)

    @_jit_static_sparse
    def seg_bwd(sparse, seg_params, state4_end, cts4, x_mask, m_mask, rng,
                offset):
        return _seg_bwd(cfg, sparse, seg_params, state4_end, cts4, x_mask,
                        m_mask, rng, offset)

    @jax.jit
    def tail_vg(head_params, refiner_params, state4, mb, rng_loss):
        def tail_loss(hp, rp, s4):
            z1, z2, o1, o2 = s4
            xm = (z1 + z2) * 0.5

            def apply_stub(p, c, s, msa, **kw):
                return alphafold2_head(hp, c, xm)

            lf = make_e2e_loss_fn(model_apply_fn=apply_stub)
            return lf({"model": {}, "refiner": rp}, ecfg, mb, rng_loss)

        return jax.value_and_grad(tail_loss, argnums=(0, 1, 2))(
            head_params, refiner_params, state4
        )

    # keys the front never reads — differentiating over them would
    # materialize a trunk-grad-sized ZERO cotangent buffer alongside the
    # real trunk grads (at depth 48 that is a whole extra trunk in HBM)
    _NON_FRONT_KEYS = ("trunk", "head_norm", "head_out")

    @jax.jit
    def front_bwd(model_params, seq3, msa, mask3, msa_mask, embedds,
                  rng_model, dx, dm):
        rest = {k: model_params[k] for k in _NON_FRONT_KEYS
                if k in model_params}
        front_sub = {k: v for k, v in model_params.items()
                     if k not in rest}

        def front_xm(p_sub):
            x, m, *_ = alphafold2_front(
                {**p_sub, **rest}, cfg, seq3, msa, mask=mask3,
                msa_mask=msa_mask, embedds=embedds, rng=rng_model,
            )
            return x, m

        _, vjp = jax.vjp(front_xm, front_sub)
        (d_params,) = vjp((dx, dm))
        return d_params

    @jax.jit
    def accum_grads(a, b):
        return jax.tree_util.tree_map(jnp.add, a, b)

    def _opt_apply(state, grads, loss):
        n = tcfg.grad_accum
        loss = loss / n
        grads = jax.tree_util.tree_map(lambda g: g / n, grads)
        updates, opt_state = opt.update(
            grads, state["opt_state"], state["params"]
        )
        params = optax.apply_updates(state["params"], updates)
        new_state = {
            "params": params,
            "opt_state": opt_state,
            "step": state["step"] + 1,
        }
        return new_state, {"loss": loss,
                           "grad_norm": optax.global_norm(grads)}

    # donate state AND grads: without donation the optimizer execution
    # holds input params+Adam state, the gradients, and the output
    # params+Adam state live at once — at depth 48 that is the two-copy
    # condition bench.py documents as not fitting the chip. Callers must
    # reassign `state = step(state, ...)` (standard donation contract).
    opt_apply = jax.jit(_opt_apply, donate_argnums=(0, 1))

    # --- one microbatch: the execution chain ------------------------------

    def microbatch_grads(params, mb, rng_loss):
        # rng chain mirrors e2e_loss_fn -> predict_structure exactly:
        # rng_loss splits into (model, mds); the tail re-splits the same
        # rng_loss internally, using mds and ignoring model
        rng_model = (
            jax.random.split(rng_loss)[0] if rng_loss is not None else None
        )
        mp = params["model"]
        seq3 = elongate(mb["seq"])
        mask3 = elongate(mb["mask"]) if mb.get("mask") is not None else None
        msa, msa_mask = mb.get("msa"), mb.get("msa_mask")
        embedds = mb.get("embedds")

        x, m, x_mask, m_mask, rng_trunk = front_fwd(
            mp, seq3, msa, mask3, msa_mask, embedds, rng_model
        )
        if m is None:
            raise ValueError("segmented step requires an MSA (or embedds) "
                             "stream — the reversible trunk does")

        def seg_slice(start, end):
            # one SLICE per use, not a held list: keeping every segment's
            # copy alive would duplicate the whole trunk on device
            return jax.tree_util.tree_map(
                lambda t: t[start:end], mp["trunk"]
            )

        state4 = (x, x, m, m)  # channel-double (models/reversible.py)
        for start, end, flag in segments:
            state4 = seg_fwd(flag, seg_slice(start, end), state4, x_mask,
                             m_mask, rng_trunk, jnp.int32(start))

        head_params = {"head_norm": mp["head_norm"],
                       "head_out": mp["head_out"]}
        loss, (d_head, d_refiner, cts4) = tail_vg(
            head_params, params["refiner"], state4, mb, rng_loss
        )

        dsegs = [None] * len(segments)
        for idx in range(len(segments) - 1, -1, -1):
            start, end, flag = segments[idx]
            state4, cts4, dsegs[idx] = seg_bwd(
                flag, seg_slice(start, end), state4, cts4, x_mask, m_mask,
                rng_trunk, jnp.int32(start)
            )

        dx1, dx2, dm1, dm2 = cts4
        d_model = front_bwd(
            mp, seq3, msa, mask3, msa_mask, embedds, rng_model,
            accum_grads(dx1, dx2), accum_grads(dm1, dm2)
        )
        # front_bwd returns only the front-read subtree; fill in the
        # trunk/head grads computed by the segment chain and the tail
        d_model = dict(d_model)
        d_model["trunk"] = jax.tree_util.tree_map(
            lambda *xs: jnp.concatenate(xs, axis=0), *dsegs
        )
        d_model["head_norm"] = d_head["head_norm"]
        d_model["head_out"] = d_head["head_out"]
        return loss, {"model": d_model, "refiner": d_refiner}

    def step(state, batch, rng=None):
        loss_sum, grad_sum = None, None
        for i in range(tcfg.grad_accum):
            mb = jax.tree_util.tree_map(lambda t: t[i], batch)
            mb_rng = (
                jax.random.fold_in(rng, i) if rng is not None else None
            )
            loss, grads = microbatch_grads(state["params"], mb, mb_rng)
            if grad_sum is None:
                loss_sum, grad_sum = loss, grads
            else:
                loss_sum = loss_sum + loss
                grad_sum = accum_grads(grad_sum, grads)
        return opt_apply(state, grad_sum, loss_sum)

    return step
