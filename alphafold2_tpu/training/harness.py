"""Training harness: jitted optax train step with scanned grad accumulation.

The reference has no Trainer abstraction at all — its loops are inlined in
entry scripts with a Python-level gradient-accumulation loop
(reference train_pre.py:72-102) and empty DeepSpeed/Lightning launcher files
(reference training_scripts/). Here the harness is a first-class subsystem:

  * one `TrainState` pytree (params, opt state, step);
  * a single jitted `train_step(state, batch, rng)` in which gradient
    accumulation is a `lax.scan` over a leading microbatch axis — the XLA
    analog of the reference's GRADIENT_ACCUMULATE_EVERY=16 Python loop,
    compiled once and free of host round-trips;
  * gradients are averaged over microbatches (the reference sums via
    repeated .backward(); under Adam the two differ only through eps —
    documented divergence, mean is the standard JAX convention).

The distributed variant of this step (mesh-sharded batch, psum-ed grads)
lives in alphafold2_tpu/parallel/.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from alphafold2_tpu.models import Alphafold2Config, alphafold2_apply, alphafold2_init
from alphafold2_tpu.ops.quant import reject_quant_training
from alphafold2_tpu.training.losses import bucketed_distance_matrix, distogram_cross_entropy


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Replaces the reference's module-level UPPER_CASE globals
    (reference train_pre.py:12-19)."""

    learning_rate: float = 3e-4
    grad_accum: int = 16
    max_grad_norm: Optional[float] = None  # reference has no clipping
    weight_decay: float = 0.0
    # learning-rate schedule (reference: constant lr only). warmup_steps
    # ramps linearly 0 -> lr; decay_steps (if set) then cosine-decays to
    # lr * decay_floor over that many post-warmup steps.
    warmup_steps: int = 0
    decay_steps: Optional[int] = None
    decay_floor: float = 0.0


def make_schedule(tcfg: TrainConfig):
    """Scalar lr schedule from the config.

    ALWAYS returns a callable (a constant schedule when no knobs are set):
    optax's opt_state carries a schedule count leaf exactly when the lr is
    a callable, so returning a float for the constant case would make the
    checkpoint pytree STRUCTURE depend on the schedule flags — a
    constant-lr restore template (e.g. predict.py's TrainConfig()) could
    then not load checkpoints from scheduled runs.

    MIGRATION NOTE: checkpoints written before schedules existed (optimizer
    built from a float lr) lack the schedule count leaf and cannot be
    restored by this version — re-init or re-train (pre-1.0 break,
    deliberate: a structure that depends on flag values is worse).
    """
    if tcfg.warmup_steps == 0 and tcfg.decay_steps is None:
        return optax.constant_schedule(tcfg.learning_rate)
    if tcfg.decay_steps is None:
        # warmup then hold (linear_schedule clamps at its end value)
        return optax.linear_schedule(
            0.0, tcfg.learning_rate, tcfg.warmup_steps
        )
    if tcfg.warmup_steps == 0:
        # decay only — no phantom zero-lr first step
        return optax.cosine_decay_schedule(
            tcfg.learning_rate, tcfg.decay_steps, alpha=tcfg.decay_floor
        )
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=tcfg.learning_rate,
        warmup_steps=tcfg.warmup_steps,
        decay_steps=tcfg.warmup_steps + tcfg.decay_steps,
        end_value=tcfg.learning_rate * tcfg.decay_floor,
    )


def make_optimizer(tcfg: TrainConfig) -> optax.GradientTransformation:
    """FIXED-ARITY chain — clip (inf = no-op) then adamw (weight_decay=0 is
    numerically plain Adam) — so the opt_state pytree structure never
    depends on flag values. A conditionally-present chain element would
    break checkpoint restore across configs (predict.py restores with a
    default TrainConfig template); see make_schedule's invariant note.
    max_grad_norm <= 0 or None means clipping off (clip(0) would silently
    zero every gradient)."""
    max_norm = (
        tcfg.max_grad_norm
        if tcfg.max_grad_norm is not None and tcfg.max_grad_norm > 0
        else float("inf")
    )
    return optax.chain(
        optax.clip_by_global_norm(max_norm),
        optax.adamw(make_schedule(tcfg), weight_decay=tcfg.weight_decay),
    )


def train_state_init(key, cfg: Alphafold2Config, tcfg: TrainConfig):
    # int8 weights are the inference-only serving arm: refuse at the
    # entry point, not as a custom-vjp error deep inside the scan
    reject_quant_training(cfg, "train_state_init")
    params = alphafold2_init(key, cfg)
    opt = make_optimizer(tcfg)
    return {
        "params": params,
        "opt_state": opt.init(params),
        "step": jnp.zeros((), jnp.int32),
    }


def make_distogram_loss_fn(apply_fn):
    """Build the distogram pretraining loss around any model apply function
    with the alphafold2_apply signature — ONE label/loss construction shared
    by the replicated and sequence-parallel training paths
    (parallel/train.py sp_distogram_loss_fn)."""

    def loss_fn(params, cfg: Alphafold2Config, batch, rng):
        labels = bucketed_distance_matrix(batch["coords"], batch["mask"])
        logits = apply_fn(
            params,
            cfg,
            batch["seq"],
            batch.get("msa"),
            mask=batch["mask"],
            msa_mask=batch.get("msa_mask"),
            rng=rng,
        )
        return distogram_cross_entropy(logits, labels)

    return loss_fn


# Distogram pretraining loss on one microbatch (reference train_pre.py:82-95).
# batch: {"seq": (b, L) int, "mask": (b, L) bool, "coords": (b, L, 3)
# C-alpha coords} and optionally {"msa": (b, r, c), "msa_mask"}.
distogram_loss_fn = make_distogram_loss_fn(alphafold2_apply)


def make_train_step(
    cfg: Alphafold2Config,
    tcfg: TrainConfig,
    loss_fn: Callable[..., Any] = distogram_loss_fn,
):
    """Build the jitted train step.

    The returned step consumes a batch whose leaves carry a leading
    microbatch axis (grad_accum, per_device_batch, ...) and scans over it.
    """
    reject_quant_training(cfg, "make_train_step")
    opt = make_optimizer(tcfg)

    def microbatch_grads(params, batch, rng):
        return jax.value_and_grad(loss_fn)(params, cfg, batch, rng)

    def train_step(state, batch, rng=None):
        params = state["params"]

        def accum(carry, inp):
            loss_sum, grad_sum = carry
            mb, i = inp
            mb_rng = jax.random.fold_in(rng, i) if rng is not None else None
            loss, grads = microbatch_grads(params, mb, mb_rng)
            return (
                loss_sum + loss,
                jax.tree_util.tree_map(jnp.add, grad_sum, grads),
            ), None

        zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
        n = tcfg.grad_accum
        (loss_sum, grad_sum), _ = jax.lax.scan(
            accum, (jnp.zeros((), jnp.float32), zeros), (batch, jnp.arange(n))
        )
        loss = loss_sum / n
        grads = jax.tree_util.tree_map(lambda g: g / n, grad_sum)

        updates, opt_state = opt.update(grads, state["opt_state"], params)
        params = optax.apply_updates(params, updates)
        new_state = {
            "params": params,
            "opt_state": opt_state,
            "step": state["step"] + 1,
        }
        return new_state, {"loss": loss, "grad_norm": optax.global_norm(grads)}

    return train_step


def make_axis_accum_train_step(
    cfg: Alphafold2Config,
    tcfg: TrainConfig,
    loss_fn: Callable[..., Any],
    axis_name: str,
    *,
    overlap: bool = True,
    bucket_elems: Optional[int] = None,
    state_init: Callable = train_state_init,
    state_shape=None,
):
    """The microbatch-accumulating train step with an EXPLICIT gradient
    reduction over `axis_name` — the axis-level body of the DP-overlap
    step (parallel/train.py `make_dp_overlap_train_step` wraps it in
    shard_map over the mesh's data axis; this builder is mesh-free so it
    stays testable and composable).

    Where `make_train_step` leaves the data-parallel all-reduce to
    GSPMD — ONE gradient psum after the whole accumulation scan, fencing
    the optimizer — this step places the collectives itself:

      * gradients flatten into a few large dtype-homogeneous buckets
        (parallel/overlap.py) so hundreds of small param leaves ride a
        handful of bandwidth-bound all-reduces instead of hundreds of
        latency-bound ones;
      * with `overlap` (default), the scan body ISSUES the psum of
        microbatch i-1's buckets before computing microbatch i's
        forward/backward — the reduction rides the interconnect under
        the next microbatch's compute, and only the LAST microbatch's
        psum remains on the critical path;
      * with `overlap=False` it accumulates locally and issues one
        bucketed psum after the scan — the synchronous reference arm
        (same arithmetic modulo psum/add reassociation; the A/B pair for
        the dryrun, bench legs, and overlap-lint fixtures).

    Loss semantics: each shard's loss_fn normalizes over ITS microbatch
    (e.g. distogram_cross_entropy's valid-pair count), and shard results
    average with equal weight. This equals the GSPMD global-batch step
    exactly when per-shard normalizers match (uniform masks / padded
    synthetic batches) and differs only in mean-of-means weighting when
    they don't — documented divergence, same convention as the
    microbatch mean `make_train_step` already takes.

    The returned step MUST run inside `shard_map` (it calls
    jax.lax.psum over `axis_name`): signature (state, batch, rng) ->
    (state, metrics) with batch leaves carrying (grad_accum,
    per_shard_batch, ...) leading axes.
    """
    from alphafold2_tpu.parallel.overlap import (
        DEFAULT_BUCKET_ELEMS,
        flatten_buckets,
        plan_buckets,
        unflatten_buckets,
    )

    reject_quant_training(cfg, "make_axis_accum_train_step")
    opt = make_optimizer(tcfg)
    n = tcfg.grad_accum
    if state_shape is None:
        # abstract trace of the init — callers that already have the
        # state shape (make_dp_overlap_train_step computes it for its
        # sharding specs) pass it in so the model is not traced twice
        state_shape = jax.eval_shape(
            lambda k: state_init(k, cfg, tcfg), jax.random.PRNGKey(0)
        )
    params_shape = state_shape["params"]
    treedef, buckets = plan_buckets(
        params_shape, bucket_elems or DEFAULT_BUCKET_ELEMS
    )

    def train_step(state, batch, rng=None):
        params = state["params"]
        num_shards = jax.lax.psum(1, axis_name)

        def bucketed_grads(mb, i):
            mb_rng = jax.random.fold_in(rng, i) if rng is not None else None
            loss, grads = jax.value_and_grad(loss_fn)(params, cfg, mb, mb_rng)
            return loss, flatten_buckets(grads, buckets)

        # microbatch 0 runs before the scan so the overlapped body always
        # has a previous microbatch's buckets in flight — no zero-filled
        # warmup psum
        loss0, bkts0 = bucketed_grads(
            jax.tree_util.tree_map(lambda x: x[0], batch), 0
        )
        zeros = [jnp.zeros_like(b) for b in bkts0]

        if n > 1:

            def accum(carry, inp):
                loss_sum, red, prev = carry
                mb, i = inp
                if overlap:
                    # ISSUE the psum of microbatch i-1 first: its
                    # transfer hides under this microbatch's fwd/bwd
                    # (the dots below do not depend on it —
                    # analysis/overlap_lint.py asserts exactly that)
                    reduced = [jax.lax.psum(b, axis_name) for b in prev]
                    loss, bkts = bucketed_grads(mb, i)
                    red = [a + r for a, r in zip(red, reduced)]
                else:
                    # synchronous arm: accumulate locally, reduce once
                    # after the scan
                    loss, bkts = bucketed_grads(mb, i)
                    bkts = [a + b for a, b in zip(prev, bkts)]
                return (loss_sum + loss, red, bkts), None

            rest = jax.tree_util.tree_map(lambda x: x[1:], batch)
            (loss_sum, red, last), _ = jax.lax.scan(
                accum, (loss0, zeros, bkts0), (rest, jnp.arange(1, n))
            )
        else:
            loss_sum, red, last = loss0, zeros, bkts0

        # flush: the last microbatch's (or, synchronous, the whole
        # accumulated) reduction — the only psum left on the critical path
        red = [a + jax.lax.psum(b, axis_name) for a, b in zip(red, last)]
        denom = n * num_shards
        loss = jax.lax.psum(loss_sum, axis_name) / denom
        grads = unflatten_buckets(
            [b / denom for b in red], params_shape, treedef, buckets
        )

        updates, opt_state = opt.update(grads, state["opt_state"], params)
        new_params = optax.apply_updates(params, updates)
        new_state = {
            "params": new_params,
            "opt_state": opt_state,
            "step": state["step"] + 1,
        }
        return new_state, {"loss": loss, "grad_norm": optax.global_norm(grads)}

    return train_step


# --- fault-injection hook (reliability layer) --------------------------------


def with_fault_injection(step_fn, injector):
    """Wrap a (jitted) step function with the chaos-injection hook point.

    The wrapper runs HOST-side, around the device program: before the
    step, the injector can raise (step-N exception, the path
    `run_resilient` recovers) or trip a preemption flag; after it, a
    `nan_grads` fault poisons the reported metrics (so StepGuard's
    non-finite watchdog must detect and roll back). `injector=None`
    returns `step_fn` unchanged — the production path pays nothing.
    """
    if injector is None:
        return step_fn

    def wrapped(state, batch, rng=None):
        step = int(np.asarray(jax.device_get(state["step"])))
        batch = injector.before_train_step(step, batch)
        new_state, metrics = step_fn(state, batch, rng)
        return injector.after_train_step(step, new_state, metrics)

    return wrapped


# --- shared trainer CLI surface ---------------------------------------------


def add_train_args(ap):
    """The optimizer/schedule/seed argparse block shared by train_pre.py and
    train_end2end.py — one place to add the next knob."""
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--seed", type=int, default=0,
                    help="base seed for params, data, and per-step rng")
    ap.add_argument("--warmup-steps", type=int, default=0,
                    help="linear lr warmup steps (0 = constant lr)")
    ap.add_argument("--decay-steps", type=int, default=None,
                    help="cosine-decay the lr over this many post-warmup steps")
    ap.add_argument("--decay-floor", type=float, default=0.0,
                    help="cosine decay ends at lr * this fraction")
    ap.add_argument("--max-grad-norm", type=float, default=None,
                    help="global-norm gradient clipping (<=0 or unset: off)")
    ap.add_argument("--weight-decay", type=float, default=0.0,
                    help="AdamW weight decay (default 0 = plain Adam)")


def tcfg_from_args(args, grad_accum: int) -> TrainConfig:
    return TrainConfig(
        learning_rate=args.lr,
        grad_accum=grad_accum,
        warmup_steps=args.warmup_steps,
        decay_steps=args.decay_steps,
        decay_floor=args.decay_floor,
        max_grad_norm=args.max_grad_norm,
        weight_decay=args.weight_decay,
    )
