"""The north-star benchmark configuration, built in exactly one place.

BASELINE.md's operational target is defined over ONE workload (config 5):
the full end-to-end structure train step — reversible tied-row trunk on
the (3*384)^2 pair grid, MSA 128 rows, aligned cross-attention, distogram
-> 200-iter MDS -> sidechain lift -> EGNN refiner -> weighted Kabsch RMSD
loss — dim 256, heads 8, bf16 compute. Three scripts time it (bench.py,
scripts/bench_sweep.py, scripts/bench_decompose.py) and their numbers are
only comparable if they run the SAME program, so the config lives here
and the scripts import it instead of hand-copying kwargs.

`smoke=True` swaps in tiny CPU-safe shapes (the driver-validated fallback
bench.py has always run off-TPU); numbers from smoke configs are
meaningless and exist only to prove the code path end-to-end.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from alphafold2_tpu.models import Alphafold2Config, RefinerConfig
from alphafold2_tpu.training.e2e import E2EConfig

NORTH_STAR_CROP = 384
NORTH_STAR_MSA_ROWS = 128
SMOKE_CROP = 16
SMOKE_MSA_ROWS = 4


def north_star_e2e_config(
    depth: int,
    *,
    smoke: bool = False,
    model_overrides: dict | None = None,
    e2e_overrides: dict | None = None,
):
    """Build the north-star E2EConfig (BASELINE.md config 5).

    Returns (ecfg, crop, msa_rows). model_overrides / e2e_overrides are
    dataclasses.replace patches on the model / e2e config respectively —
    the sweep's tuning knobs go through here so a knob rename breaks
    loudly in every script at once.
    """
    crop = SMOKE_CROP if smoke else NORTH_STAR_CROP
    msa_rows = SMOKE_MSA_ROWS if smoke else NORTH_STAR_MSA_ROWS
    dim, dim_head = (32, 16) if smoke else (256, 64)
    dtype = jnp.float32 if smoke else jnp.bfloat16

    model = Alphafold2Config(
        dim=dim,
        depth=depth,
        heads=8,
        dim_head=dim_head,
        max_seq_len=2048,
        max_num_msa=max(msa_rows, 20),
        dtype=dtype,
        # O(1) trunk activation memory in depth — mandatory at depth 48
        reversible=True,
        msa_tie_row_attn=True,
        cross_attn_compress_ratio=1 if smoke else 4,
        # column-aligned cross-attention: the O(n^2 * r) redesign that makes
        # this workload tractable (flat mode is O(n^2 * r*c) — ~100x more)
        cross_attn_mode="aligned",
        attn_flash="auto",
        # chunk attention ops over the folded-batch axis so QKV/out
        # projections never materialize over all 1.3M pair tokens
        attn_batch_chunk=0 if smoke else 32,
        # bound the 2048-wide GEGLU intermediate on the pair stream
        ff_chunk_size=0 if smoke else 32768,
    )
    if model_overrides:
        model = dataclasses.replace(model, **model_overrides)

    rdim = 16 if smoke else 64
    ecfg = E2EConfig(
        model=model,
        refiner=RefinerConfig(
            num_tokens=14, dim=rdim, depth=2, msg_dim=rdim, dtype=dtype,
            # bound the (A, A, msg) pair-message tensor at 5376 atoms
            atom_chunk=0 if smoke else 256,
        ),
        mds_iters=5 if smoke else 200,  # reference train_end2end.py:157
    )
    if e2e_overrides:
        ecfg = dataclasses.replace(ecfg, **e2e_overrides)
    return ecfg, crop, msa_rows
