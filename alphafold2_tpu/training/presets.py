"""The north-star benchmark configuration, built in exactly one place.

BASELINE.md's operational target is defined over ONE workload (config 5):
the full end-to-end structure train step — reversible tied-row trunk on
the (3*384)^2 pair grid, MSA 128 rows, aligned cross-attention, distogram
-> 200-iter MDS -> sidechain lift -> EGNN refiner -> weighted Kabsch RMSD
loss — dim 256, heads 8, bf16 compute. Three scripts time it (bench.py,
scripts/bench_sweep.py, scripts/bench_decompose.py) and their numbers are
only comparable if they run the SAME program, so the config lives here
and the scripts import it instead of hand-copying kwargs.

Three tiers exist: "north_star" (the real target), "smoke" (tiny
CPU-safe shapes — the driver-validated fallback bench.py runs off-TPU;
numbers are meaningless and exist only to prove the code path
end-to-end), and "proportional" (1/8-crop shapes preserving the north
star's structural ratios — what the multichip dryrun's scaled leg and
MULTICHIP_r0N.json measure). `smoke=True` is the legacy spelling of
tier="smoke".
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from alphafold2_tpu.models import Alphafold2Config, RefinerConfig
from alphafold2_tpu.models.config import depth_aware_attn_defaults
from alphafold2_tpu.training.e2e import E2EConfig

NORTH_STAR_CROP = 384
NORTH_STAR_MSA_ROWS = 128
SMOKE_CROP = 16
SMOKE_MSA_ROWS = 4
# the PROPORTIONAL tier keeps the north star's structural ratios —
# crop : MSA rows = 3:1, compress ratio 4, aligned cross, reversible
# tied-row trunk — at 1/8 the crop so an 8-device CPU mesh can
# compile AND execute it in minutes (the multichip dryrun's scaled
# config, VERDICT r2 weak #5)
PROPORTIONAL_CROP = 48
PROPORTIONAL_MSA_ROWS = 16


def north_star_e2e_config(
    depth: int,
    *,
    smoke: bool = False,
    tier: str | None = None,
    model_overrides: dict | None = None,
    e2e_overrides: dict | None = None,
):
    """Build the north-star E2EConfig (BASELINE.md config 5).

    Returns (ecfg, crop, msa_rows). model_overrides / e2e_overrides are
    dataclasses.replace patches on the model / e2e config respectively —
    the sweep's tuning knobs go through here so a knob rename breaks
    loudly in every script at once. `tier` selects "north_star"
    (default), "smoke" (tiny CPU validation shapes), or "proportional"
    (scaled-down-but-ratio-preserving, for the multichip dryrun);
    smoke=True is the legacy spelling of tier="smoke".
    """
    if smoke and tier not in (None, "smoke"):
        raise ValueError(f"smoke=True conflicts with tier={tier!r}")
    tier = tier or ("smoke" if smoke else "north_star")
    smoke = tier == "smoke"
    # one row per tier: crop, msa_rows, dim, dim_head, compress, rdim,
    # mds iters, mds init. The north-star MDS cut (25 iterations off a
    # classical Torgerson warm start) is the PROMOTED default since PR 7:
    # classical init reaches the random-init stress floor in ~1 iteration
    # on exact and distogram-censored inputs, and e2e smoke training with
    # (25, classical) tracks (200, random) at equal-or-lower loss
    # (PERF.md round 4). The retired reference arm (200, random —
    # reference train_end2end.py:157) stays reachable via e2e_overrides /
    # train_end2end.py --mds-reference for parity runs, and the
    # `e2e_mds200random` sweep leg measures it against this default.
    crop, msa_rows, dim, dim_head, compress, rdim, mds_iters, mds_init = {
        "north_star": (NORTH_STAR_CROP, NORTH_STAR_MSA_ROWS, 256, 64, 4, 64,
                       25, "classical"),
        "smoke": (SMOKE_CROP, SMOKE_MSA_ROWS, 32, 16, 1, 16, 5, "random"),
        "proportional": (PROPORTIONAL_CROP, PROPORTIONAL_MSA_ROWS, 64, 16, 4,
                         32, 25, "random"),
    }[tier]
    dtype = jnp.bfloat16 if tier == "north_star" else jnp.float32
    # measured-headroom attention knobs, resolved by depth (PERF.md item
    # 1): depth <= 24 raises chunk/tile, depth 48 keeps the proven values
    attn_knobs = (
        depth_aware_attn_defaults(depth)
        if tier == "north_star"
        else {"attn_batch_chunk": 0, "attn_flash_tile_elems": 1 << 25}
    )

    model = Alphafold2Config(
        dim=dim,
        depth=depth,
        heads=8,
        dim_head=dim_head,
        max_seq_len=2048,
        max_num_msa=max(msa_rows, 20),
        dtype=dtype,
        # O(1) trunk activation memory in depth — mandatory at depth 48
        reversible=True,
        msa_tie_row_attn=True,
        cross_attn_compress_ratio=compress,
        # column-aligned cross-attention: the O(n^2 * r) redesign that makes
        # this workload tractable (flat mode is O(n^2 * r*c) — ~100x more)
        cross_attn_mode="aligned",
        attn_flash="auto",
        # chunk attention ops over the folded-batch axis so QKV/out
        # projections never materialize over all 1.3M pair tokens (only
        # needed at north-star scale; chunking tiny shapes just adds
        # lax.map dispatch). Chunk and tile sizes are depth-aware
        # (models/config.py depth_aware_attn_defaults)
        # bound the 2048-wide GEGLU intermediate on the pair stream
        ff_chunk_size=32768 if tier == "north_star" else 0,
        **attn_knobs,
    )
    if model_overrides:
        model = dataclasses.replace(model, **model_overrides)

    ecfg = E2EConfig(
        model=model,
        refiner=RefinerConfig(
            num_tokens=14, dim=rdim, depth=2, msg_dim=rdim, dtype=dtype,
            # bound the (A, A, msg) pair-message tensor at 5376 atoms
            atom_chunk=256 if tier == "north_star" else 0,
        ),
        mds_iters=mds_iters,
        mds_init=mds_init,
    )
    if e2e_overrides:
        ecfg = dataclasses.replace(ecfg, **e2e_overrides)
    return ecfg, crop, msa_rows
