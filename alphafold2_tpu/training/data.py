"""Data pipeline: synthetic protein batches + sidechainnet adapter.

The reference feeds sidechainnet batches straight into the model with
dynamic lengths, filtering on `len < 250` at iteration time
(reference train_pre.py:44-55). XLA wants static shapes, so this adapter
does the shape discipline on the host: proteins are cropped/padded to a
fixed `max_len` and batches always have identical shapes, with validity
carried in the mask. Length filtering becomes crop-or-pad instead of skip.

Synthetic data generates protein-like C-alpha traces (fixed-step random
walk, ~3.8 A bond length) so the training loop and benchmarks run without
any dataset download.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Optional

import numpy as np

from alphafold2_tpu.constants import NUM_AMINO_ACIDS


@dataclasses.dataclass(frozen=True)
class DataConfig:
    batch_size: int = 1
    max_len: int = 128
    msa_rows: int = 0  # 0 = sequence-only (the train_pre path)
    seed: int = 0


def _batch_rng(seed: int, index: int) -> np.random.RandomState:
    """Per-batch RandomState derived from (stream seed, batch index).

    Batch `i` is a pure function of the index, so a resumed run re-creates
    the stream at any position in O(1) instead of replaying `i` batches
    (the reference-era replay was O(steps) — VERDICT r1 weakness #6)."""
    return np.random.RandomState((seed * 1_000_003 + index) % (2**31 - 1))


def synthetic_batches(cfg: DataConfig, start_index: int = 0) -> Iterator[dict]:
    """Endless protein-like batches with static shapes.

    Yields {"seq": (b, L) int32, "mask": (b, L) bool, "coords": (b, L, 3)
    float32} (+ msa/msa_mask when cfg.msa_rows > 0). `start_index` jumps the
    stream to that batch index in O(1).
    """
    b, L = cfg.batch_size, cfg.max_len
    index = start_index
    while True:
        rng = _batch_rng(cfg.seed, index)
        index += 1
        seq = rng.randint(0, NUM_AMINO_ACIDS, size=(b, L)).astype(np.int32)
        lengths = rng.randint(max(8, L // 2), L + 1, size=(b,))
        mask = np.arange(L)[None, :] < lengths[:, None]
        # C-alpha trace: unit-step random walk scaled to ~3.8 A
        steps = rng.randn(b, L, 3).astype(np.float32)
        steps /= np.linalg.norm(steps, axis=-1, keepdims=True) + 1e-8
        coords = np.cumsum(3.8 * steps, axis=1).astype(np.float32)
        batch = {"seq": seq, "mask": mask, "coords": coords}
        if cfg.msa_rows > 0:
            batch["msa"] = rng.randint(
                0, NUM_AMINO_ACIDS, size=(b, cfg.msa_rows, L)
            ).astype(np.int32)
            batch["msa_mask"] = np.broadcast_to(mask[:, None, :], batch["msa"].shape)
        yield batch


def synthetic_structure_batches(cfg: DataConfig, start_index: int = 0) -> Iterator[dict]:
    """Endless full-atom batches for the end-to-end structure workload
    (reference train_end2end.py's sidechainnet crd tensor, reshaped
    (b, L, 14, 3)).

    The backbone is a noisy helix with protein-like chirality
    (mostly-negative phi dihedrals), so the MDS mirror fix in the pipeline
    resolves to the correct enantiomer; sidechain slots are parked at the
    carbonyl C exactly like `sidechain_container` does, giving a
    reachable target for the refiner.
    """
    from alphafold2_tpu.geometry import sidechain_container

    b, L = cfg.batch_size, cfg.max_len
    index = start_index
    while True:
        rng = _batch_rng(cfg.seed, index)
        index += 1
        seq = rng.randint(0, NUM_AMINO_ACIDS, size=(b, L)).astype(np.int32)
        mask = np.ones((b, L), bool)
        t = 0.6 * np.arange(3 * L)[None, :, None]
        helix = np.concatenate(
            [2 * np.cos(t), 2 * np.sin(t), -0.16 * t], axis=-1
        ).astype(np.float32)
        backbone = helix + 0.05 * rng.randn(b, 3 * L, 3).astype(np.float32)
        cloud = np.asarray(sidechain_container(backbone, place_oxygen=True))
        batch = {"seq": seq, "mask": mask, "coords": cloud}
        if cfg.msa_rows > 0:
            batch["msa"] = rng.randint(
                0, NUM_AMINO_ACIDS, size=(b, cfg.msa_rows, L)
            ).astype(np.int32)
            batch["msa_mask"] = np.broadcast_to(mask[:, None, :], batch["msa"].shape)
        yield batch


def stack_microbatches(it: Iterator[dict], grad_accum: int) -> Iterator[dict]:
    """Group `grad_accum` batches under a leading microbatch axis for the
    scanned accumulation in the train step."""
    while True:
        mbs = [next(it) for _ in range(grad_accum)]
        yield {k: np.stack([m[k] for m in mbs]) for k in mbs[0]}


def synthetic_microbatch_fn(cfg: DataConfig, grad_accum: int, source=None):
    """Step-indexed microbatch fetch: `fetch(step)` is a PURE function of
    the step number (synthetic streams are index-pure), so a retried or
    resumed step re-fetches the IDENTICAL batch — the property that lets
    the chaos suite assert bit-exact recovery, and `run_resilient` replay
    a crashed step instead of silently training it on the next batch.

    `source`: synthetic_batches (default) or synthetic_structure_batches.
    """
    src = source if source is not None else synthetic_batches

    def fetch(step: int) -> dict:
        it = src(cfg, start_index=step * grad_accum)
        mbs = [next(it) for _ in range(grad_accum)]
        return {k: np.stack([m[k] for m in mbs]) for k in mbs[0]}

    return fetch


# --- per-process pipeline (multi-host) ---------------------------------------
#
# The pod data contract (ScaleFold, arxiv 2404.11068; SNIPPETS [3]'s
# DataParallelPartitioner): the GLOBAL batch is per-process batch x
# process count, every process's pipeline yields ONLY its own rows, and
# the train step consumes one global jax.Array assembled from the local
# shards (`compat.make_array_from_process_local_data`). Nothing below
# imports the mesh machinery at module scope, so the data layer stays
# importable host-side without touching parallel/.


def process_shard(batch: dict, *, index: Optional[int] = None,
                  count: Optional[int] = None, axis: int = 0) -> dict:
    """This process's rows of a host-side GLOBAL batch.

    `axis` is the batch axis (0 for plain batches, 1 for microbatched
    (accum, b, ...) stacks). Rows [index * b/count, (index+1) * b/count)
    — concatenating every process's shard along `axis` reconstructs the
    global batch exactly, which is what makes the multi-process loss
    bit-identical to the single-process twin on the same stream. Scalars
    and non-array entries (e.g. the `bucket` tag) pass through."""
    import jax

    if index is None:
        index = jax.process_index()
    if count is None:
        count = jax.process_count()

    def shard(x):
        if not hasattr(x, "ndim") or x.ndim <= axis:
            return x
        b = x.shape[axis]
        if b % count != 0:
            raise ValueError(
                f"global batch axis {b} must divide across {count} "
                "processes (global batch = per-process batch x process "
                "count)"
            )
        lo = index * (b // count)
        sl = [slice(None)] * x.ndim
        sl[axis] = slice(lo, lo + b // count)
        return x[tuple(sl)]

    return {k: shard(v) for k, v in batch.items()}


def shard_items(items: Iterator, *, index: Optional[int] = None,
                count: Optional[int] = None) -> Iterator:
    """Process-strided view of a record stream (for real corpus sources:
    each process KEEPS every count-th record starting at its index and
    never materializes the rest — feed the result to `bucket_batches`).
    Synthetic index-pure sources use `process_shard` row slicing instead,
    which preserves bit-exactness against the single-process stream."""
    import jax

    if index is None:
        index = jax.process_index()
    if count is None:
        count = jax.process_count()
    for i, item in enumerate(items):
        if i % count == index:
            yield item


def per_process_microbatch_fn(cfg: DataConfig, grad_accum: int, source=None,
                              *, index: Optional[int] = None,
                              count: Optional[int] = None):
    """`synthetic_microbatch_fn` for one process of a pod: `fetch(step)`
    returns only this process's rows of the step's GLOBAL microbatch
    stack (cfg.batch_size is the GLOBAL batch). Still a pure function of
    the step index, so retries/resume stay replay-exact, and
    `resilient_batches` composes underneath exactly as single-process."""
    base = synthetic_microbatch_fn(cfg, grad_accum, source=source)

    def fetch(step: int) -> dict:
        return process_shard(base(step), index=index, count=count, axis=1)

    return fetch


def assemble_global_batch(local_batch: dict, mesh, *,
                          microbatched: bool = True,
                          count: Optional[int] = None) -> dict:
    """Global jax.Arrays from this process's host-side shard.

    Each leaf's batch axis (axis 1 when `microbatched`, else 0) scales by
    the process count and shards over the mesh's "data" axis; every
    other axis stays replicated. Single-process this degenerates to a
    device_put with the same shardings, so the single-process twin can
    run the identical code path. Non-array entries pass through."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from alphafold2_tpu import compat

    if count is None:
        count = jax.process_count()
    axis = 1 if microbatched else 0

    def assemble(x):
        if not hasattr(x, "ndim") or x.ndim <= axis:
            return x
        parts = [None] * x.ndim
        if "data" in mesh.axis_names:
            parts[axis] = "data"
        sharding = NamedSharding(mesh, PartitionSpec(*parts))
        global_shape = list(x.shape)
        global_shape[axis] = x.shape[axis] * count
        return compat.make_array_from_process_local_data(
            sharding, np.asarray(x), tuple(global_shape)
        )

    return {k: assemble(v) for k, v in local_batch.items()}


class ResilientBatches:
    """Retrying/skipping wrapper over a batch source — the data-pipeline
    answer to a flaky filesystem or a corrupt shard: a failed fetch is
    retried with bounded exponential backoff, and a record that keeps
    failing is SKIPPED (counted, reported) instead of killing a multi-day
    run. StopIteration is end-of-data, not a fault, and passes through.

    Wraps either an iterator (`next` semantics) or a step-indexed fetch
    callable (`fetch(step)`, e.g. `synthetic_microbatch_fn`) — in the
    callable form a retry re-fetches the SAME step, keeping recovery
    bit-exact. The chaos hook (`injector.before_batch(index)`) fires
    before each underlying fetch, so an injected transient error never
    consumes a record: retry really does see the same data.

    Iterating yields batches; in callable form use `fetch(step)` directly.
    """

    def __init__(self, source, *, max_retries: int = 2,
                 backoff_s: float = 0.05, max_backoff_s: float = 2.0,
                 injector=None, max_skipped: Optional[int] = None):
        self._it = iter(source) if not callable(source) else None
        self._fn = source if callable(source) else None
        self.max_retries = max_retries
        self.backoff_s = backoff_s
        self.max_backoff_s = max_backoff_s
        self._injector = injector
        self.max_skipped = max_skipped
        self.skipped = 0      # records abandoned after retries
        self.retries = 0      # total retry attempts (observability)
        self._index = 0       # fetch index (the chaos hook's clock)

    def _attempt(self, step: Optional[int]):
        index = self._index
        self._index += 1
        if self._injector is not None:
            self._injector.before_batch(index)
        if self._fn is not None:
            return self._fn(step if step is not None else index)
        return next(self._it)

    def _fetch(self, step: Optional[int] = None):
        import time as _time

        while True:  # per-record loop: a skip moves on to the next record
            for attempt in range(self.max_retries + 1):
                try:
                    return self._attempt(step)
                except StopIteration:
                    raise
                except Exception as e:
                    if attempt < self.max_retries:
                        self.retries += 1
                        delay = min(self.backoff_s * (2 ** attempt),
                                    self.max_backoff_s)
                        if delay > 0:
                            _time.sleep(delay)
                        continue
                    self.skipped += 1
                    print(f"data: record at fetch index {self._index - 1} "
                          f"failed {attempt + 1} attempts "
                          f"({type(e).__name__}: {e}) — skipped "
                          f"({self.skipped} total)")
                    if (self.max_skipped is not None
                            and self.skipped > self.max_skipped):
                        raise RuntimeError(
                            f"data pipeline skipped {self.skipped} records "
                            f"(> max_skipped={self.max_skipped}); the source "
                            "is broken, not flaky"
                        ) from e
            # skipped: fall through and fetch the next record. In callable
            # form the step's batch is unrecoverable by definition here, so
            # serve the next index's batch for it — logged above, and the
            # skipped counter keeps the divergence visible.
            if self._fn is not None:
                step = None

    def __iter__(self):
        return self

    def __next__(self):
        return self._fetch()

    def __call__(self, step: int):
        return self._fetch(step)


def resilient_batches(source, **kwargs) -> ResilientBatches:
    """Convenience constructor, the documented data-pipeline hook point
    (see reliability.faults): `resilient_batches(it, injector=...)`."""
    return ResilientBatches(source, **kwargs)


def bucket_batches(
    items: Iterator[tuple],
    cfg: DataConfig,
    buckets: tuple,
    full_atom: bool = False,
) -> Iterator[dict]:
    """Static-shape LENGTH BUCKETING over a stream of variable-length
    proteins (SURVEY.md hard-part #3: the reference filters `len < 250`
    dynamically, reference train_pre.py:54 — XLA wants a small closed set
    of shapes instead).

    items: yields (seq_ints (L,), cloud (L, 14, 3)) pairs of arbitrary L —
      the native prefetch pool's item layout (runtime/native.py).
    buckets: ascending lengths, e.g. (64, 128, 256). A protein goes to the
      smallest bucket that holds it (cropped to the largest otherwise),
      padded to the bucket length; a batch is emitted when its bucket has
      `cfg.batch_size` proteins. Each emitted batch carries a `bucket` key
      (python int — jit recompiles once per bucket, then caches).

    Yields the same dict layout as the other sources: seq/mask + coords
    (b, L, 3) C-alpha, or full_atom coords (b, L, 14, 3) + atom_mask.
    """
    buckets = tuple(sorted(set(int(x) for x in buckets)))
    if not buckets:
        raise ValueError("need at least one bucket length")
    rng = np.random.RandomState(cfg.seed)
    pending: dict = {bl: [] for bl in buckets}
    b = cfg.batch_size
    for seq, cloud in items:
        L = len(seq)
        bl = next((x for x in buckets if L <= x), buckets[-1])
        start = rng.randint(0, L - bl + 1) if L > bl else 0  # random crop,
        # matching the native loader's policy (csrc/af2_runtime.cc fill_row)
        pending[bl].append(
            (np.asarray(seq)[start : start + bl], np.asarray(cloud)[start : start + bl])
        )
        if len(pending[bl]) < b:
            continue
        group, pending[bl] = pending[bl], []
        seq_out = np.zeros((b, bl), np.int32)
        mask = np.zeros((b, bl), bool)
        cloud_out = np.zeros((b, bl, 14, 3), np.float32)
        for row, (s, c) in enumerate(group):
            n = min(len(s), len(c))
            seq_out[row, :n] = s[:n]
            cloud_out[row, :n] = c[:n]
            mask[row, :n] = True
        batch = {"seq": seq_out, "mask": mask, "bucket": bl}
        if full_atom:
            batch["coords"] = cloud_out
            batch["atom_mask"] = np.abs(cloud_out).sum(-1) > 0
        else:
            batch["coords"] = cloud_out[:, :, 1]  # C-alpha slot
        yield batch


def bucketed_microbatches(it: Iterator[dict], grad_accum: int) -> Iterator[dict]:
    """stack_microbatches for a bucketed stream: microbatches in one
    stacked group must share a shape, so accumulation is per bucket —
    groups are emitted as soon as any bucket has `grad_accum` batches."""
    pending: dict = {}
    for batch in it:
        bl = batch["bucket"]
        pending.setdefault(bl, []).append(batch)
        if len(pending[bl]) < grad_accum:
            continue
        mbs = pending.pop(bl)
        out = {
            k: np.stack([m[k] for m in mbs]) for k in mbs[0] if k != "bucket"
        }
        out["bucket"] = bl
        yield out


def _sidechainnet_gen(
    cfg: DataConfig,
    casp_version: int,
    thinning: int,
    split: str,
    full_atom: bool,
) -> Optional[Iterator[dict]]:
    """Shared sidechainnet adapter (reference train_pre.py:44-55 /
    train_end2end.py:107-120), crop/padded to static (b, max_len) shapes.
    Returns None when sidechainnet is absent (optional dependency, as in the
    reference).

    full_atom=False: {"coords": (b, L, 3)} C-alpha traces (train_pre).
    full_atom=True:  {"coords": (b, L, 14, 3), "atom_mask": (b, L, 14)} —
    per-ATOM resolution mask, because sidechainnet zero-pads unresolved
    atoms: a residue whose C-alpha resolved but whose side chain did not
    would otherwise enter the loss with ground truth at the origin.
    """
    try:
        import sidechainnet as scn  # type: ignore
    except Exception:
        return None

    data = scn.load(casp_version=casp_version, thinning=thinning)

    def gen():
        rng = np.random.RandomState(cfg.seed)
        b, L = cfg.batch_size, cfg.max_len
        seqs, coords_all = data[split]["seq"], data[split]["crd"]
        order = np.arange(len(seqs))
        while True:
            rng.shuffle(order)
            for start in range(0, len(order) - b + 1, b):
                idx = order[start : start + b]
                seq = np.zeros((b, L), np.int32)
                mask = np.zeros((b, L), bool)
                cloud = np.zeros((b, L, 14, 3), np.float32)
                for row, i in enumerate(idx):
                    s = _encode_seq(seqs[i])[:L]
                    c = np.asarray(coords_all[i], np.float32).reshape(-1, 14, 3)[
                        : len(s)
                    ]
                    n = min(len(s), len(c))
                    seq[row, :n] = s[:n]
                    cloud[row, :n] = c[:n]
                    # residue valid when its C-alpha (atom slot 1) resolved
                    mask[row, :n] = np.abs(c[:n, 1]).sum(-1) > 0
                batch = {"seq": seq, "mask": mask}
                if full_atom:
                    batch["coords"] = cloud
                    batch["atom_mask"] = np.abs(cloud).sum(-1) > 0
                else:
                    batch["coords"] = cloud[:, :, 1]
                yield batch

    return gen()


def sidechainnet_batches(
    cfg: DataConfig,
    casp_version: int = 12,
    thinning: int = 30,
    split: str = "train",
) -> Optional[Iterator[dict]]:
    """C-alpha sidechainnet adapter for distogram pretraining
    (reference train_pre.py:44-55)."""
    return _sidechainnet_gen(cfg, casp_version, thinning, split, full_atom=False)


def sidechainnet_structure_batches(
    cfg: DataConfig,
    casp_version: int = 12,
    thinning: int = 30,
    split: str = "train",
) -> Optional[Iterator[dict]]:
    """Full-atom sidechainnet adapter for the end-to-end structure loss
    (reference train_end2end.py:107-120), with a per-atom resolution mask."""
    return _sidechainnet_gen(cfg, casp_version, thinning, split, full_atom=True)


_AA = "ACDEFGHIKLMNPQRSTVWY"
_AA_IDX = {a: i for i, a in enumerate(_AA)}


def _encode_seq(s: str) -> np.ndarray:
    return np.asarray([_AA_IDX.get(c, NUM_AMINO_ACIDS - 1) for c in s], np.int32)
