"""Benchmark: the NORTH-STAR workload — end-to-end structure training
(trunk -> distogram -> MDS -> sidechain lift -> SE(3) refiner -> Kabsch
RMSD loss) at crop=384, MSA=128, depth=48, bf16, reversible trunk, on one
chip — plus inference sec/protein (BASELINE.md operational target).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
The reference publishes no numbers (BASELINE.md), so vs_baseline is against
the driver-defined operational target of 1.0 optimizer step/sec/chip.
Extras: achieved TFLOP/s and MFU (model FLOPs from the compiled
executable's cost analysis over the chip's peak), and inference
sec/protein for the predict flow.

Methodology: K optimizer steps run INSIDE one jitted `lax.scan`, and the
per-step losses are fetched to the host before stopping the clock. This is
deliberate: on remotely-dispatched backends (the axon tunnel),
`block_until_ready` returns before device execution finishes, so a Python
step loop measures dispatch latency, not compute — fetching the results is
the only timing the backend cannot fake.
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np

# bf16 peak FLOP/s by TPU generation (public spec sheets)
_PEAK_FLOPS = (
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v6", 918e12),
    ("v4", 275e12),
    ("v3", 123e12),
)


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in _PEAK_FLOPS:
        if key in kind:
            return peak
    return 197e12  # default to v5e


def _compiled_flops(compiled) -> float:
    """Model FLOPs of one executable from XLA cost analysis (0 if opaque)."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        return float(ca.get("flops", 0.0))
    except Exception:
        return 0.0


def main():
    import argparse
    import os
    import subprocess
    import sys

    ap = argparse.ArgumentParser()
    ap.add_argument("--single-depth", type=int, default=None)
    ap.add_argument("--segments", type=int, default=0,
                    help="run the train step as this many reversible trunk "
                         "segments in SEPARATE device executions "
                         "(training/segmented.py) — the tunneled worker "
                         "kills single executions beyond ~60 s of device "
                         "time, which a monolithic depth-48 step exceeds")
    args = ap.parse_args()

    if args.single_depth is not None:
        dev = jax.devices()[0]
        print(json.dumps(_run(dev, dev.platform == "tpu", args.single_depth,
                              segments=args.segments)))
        return

    # The orchestrating parent NEVER initializes JAX: a wedged TPU tunnel
    # (observed after worker crashes) hangs backend init indefinitely, and
    # the parent must stay alive to fall back. A SUBPROCESS probe (a real
    # matmul, not just backend init — a wedged relay can enumerate devices
    # yet hang every execution) decides whether a healthy TPU is reachable.
    # The probe RETRIES with backoff over a window: round 2's official
    # artifact lost its TPU measurement to a single failed probe
    # (BENCH_r02.json), so one transient tunnel failure must never again
    # decide the round. Window configurable via AF2_BENCH_PROBE_WINDOW_SEC
    # (0 = single probe).
    probe_script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "scripts", "tpu_probe.py")

    def probe_once(timeout=240):
        """-> 'healthy' | 'no-tpu' (deterministic, don't retry) |
        'transient' (timeout / crash before the platform print)."""
        try:
            probe = subprocess.run(
                [sys.executable, probe_script],
                capture_output=True, text=True, timeout=timeout,
            )
        except subprocess.TimeoutExpired:
            return "transient"
        if probe.returncode == 0 and "tpu-healthy" in probe.stdout:
            return "healthy"
        # backend init succeeded but the platform is not TPU: this host
        # has no TPU at all — retrying cannot change that
        if "platform:" in probe.stdout and "tpu" not in probe.stdout:
            return "no-tpu"
        return "transient"

    probe_window = float(os.environ.get("AF2_BENCH_PROBE_WINDOW_SEC", 3600))
    probe_deadline = time.monotonic() + probe_window
    status, n_probes = probe_once(), 1
    while status == "transient" and time.monotonic() < probe_deadline:
        # backoff 1,2,...,8 min cap, clamped to the remaining window
        wait = min(480, 60 * n_probes,
                   max(1, probe_deadline - time.monotonic()))
        print(f"TPU probe {n_probes} failed; retrying in {wait:.0f}s "
              f"(window ends in "
              f"{max(0, probe_deadline - time.monotonic()):.0f}s)",
              file=sys.stderr, flush=True)
        time.sleep(wait)
        status = probe_once()
        n_probes += 1
    tpu_env = status == "healthy"
    if not tpu_env:
        print(f"TPU health probe failed {n_probes}x ({status}) over "
              f"{probe_window:.0f}s; benching CPU smoke config only",
              file=sys.stderr)

    # Depth ladder at the north-star crop/MSA (BASELINE.md config 5 is
    # depth 48). Ordering: depth 24 FIRST — it is known to complete within
    # the tunneled worker's ~60 s single-execution budget, while depth 48
    # (~96 s/step) has CRASHED the worker, and a crashed worker wedges the
    # relay for hours (every later backend init hangs). Securing the
    # shallower on-chip measurement before attempting the deeper one means
    # a depth-48 wedge costs the upgrade, not the whole measurement. The
    # terminal CPU smoke entry guarantees the driver always records a line.

    def attempt(depth, platform, timeout, disable_kernel=False, segments=0):
        env = dict(os.environ)
        if platform == "cpu":
            env.pop("PALLAS_AXON_POOL_IPS", None)
            env["JAX_PLATFORMS"] = "cpu"
        if disable_kernel:
            env["AF2_DISABLE_FLASH_KERNEL"] = "1"
        def salvage(stdout, label):
            # salvage a partial measurement: the worker prints the train
            # numbers BEFORE the inference leg, so a crash or hang there (a
            # long single forward execution) must not cost the whole attempt
            if isinstance(stdout, bytes):
                stdout = stdout.decode("utf-8", "replace")
            for line in reversed((stdout or "").strip().splitlines()):
                try:
                    partial = json.loads(line)
                except ValueError:
                    continue
                # a complete result (inference leg finished) that exited
                # nonzero afterwards is a teardown failure, not a partial
                # measurement — don't mislabel it
                if partial.get("inference_sec_per_protein") is None:
                    partial[label] = True
                return partial
            return None

        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--single-depth", str(depth),
                 *(["--segments", str(segments)] if segments else [])],
                capture_output=True, text=True, env=env, timeout=timeout,
            )
        except subprocess.TimeoutExpired as e:
            # the train row may already be on stdout (e.g. the inference
            # leg hung): keep it rather than discarding the measurement —
            # but still flag timed_out so the ladder stops driving a
            # now-suspect tunnel
            partial = salvage(e.stdout,
                              "worker_timed_out_after_train_measurement")
            if partial is not None:
                return partial, None, True
            # structured flag, not message-sniffing: stderr text may contain
            # its own unrelated "timed out" wording
            return None, f"depth-{depth} hit the {timeout}s timeout", True
        if proc.returncode != 0:
            partial = salvage(proc.stdout,
                              "worker_crashed_after_train_measurement")
            if partial is not None:
                return partial, None, False
            err = (proc.stderr or "").strip().splitlines()
            return None, (err[-1] if err else f"rc={proc.returncode}"), False
        for line in reversed(proc.stdout.strip().splitlines()):
            try:
                return json.loads(line), None, False
            except ValueError:
                continue
        return None, "subprocess succeeded but printed no JSON", False

    best, best_depth, errors = None, None, []
    if tpu_env:
        # depth 24 runs monolithic (fits the worker's ~60 s single-execution
        # budget); depth 48 runs SEGMENTED (training/segmented.py, 4 trunk
        # segments -> every device execution stays ~16 s or less) — the
        # monolithic depth-48 step is ~96 s in one execution and CRASHES
        # the tunneled worker (PERF.md), which is why it went unmeasured
        # for four sessions
        for depth, segments in ((24, 0), (48, 4)):
            budget = 2400 + (600 if segments else 0)
            result, err, timed_out = attempt(
                depth, None, timeout=budget, segments=segments,
            )
            if result is None and not timed_out:
                # non-timeout failure: retry once with the Pallas kernel
                # disabled, so a kernel-compile regression costs the fused
                # path, not the whole on-chip measurement (same budget —
                # the XLA fallback is the slower path)
                errors.append(err)
                result, err, timed_out = attempt(
                    depth, None, timeout=budget, disable_kernel=True,
                    segments=segments,
                )
                if result is not None:
                    result["flash_kernel_disabled"] = True
            if result is not None:
                best, best_depth = result, depth  # deeper attempts overwrite
                if timed_out:
                    # train row salvaged but the worker then hung: keep
                    # the measurement, stop driving the suspect tunnel
                    errors.append(f"depth-{depth} worker hung after the "
                                  "train measurement")
                    break
                continue
            errors.append(err)
            if timed_out:
                break  # wedged tunnel: later attempts would hang too
    if best is None:
        result, err, _ = attempt(2, "cpu", timeout=2400)
        if result is None:
            raise RuntimeError(f"all bench attempts failed; last: {err}")
        best = result
        if tpu_env:
            best["fallback_from_depth"] = 48
        else:
            best["fallback_reason"] = (
                f"TPU health probe failed {n_probes}x ({status}) over "
                f"{probe_window:.0f}s")
    elif errors and best_depth != 48:
        # an on-TPU measurement survived but the north-star depth did not:
        # mark the kept shallower result as a fallback (PERF.md contract).
        # A depth-48 result that needed the kernel-disabled retry is NOT a
        # fallback — flash_kernel_disabled already records the degradation
        best["fallback_from_depth"] = 48
        best["fallback_reason"] = errors[-1][-200:]
    if errors:
        best["failed_attempts"] = "; ".join(e[-120:] for e in errors)
    print(json.dumps(best))


def _run(dev, on_tpu: bool, depth: int, segments: int = 0) -> dict:
    from alphafold2_tpu.training import (
        DataConfig,
        TrainConfig,
        e2e_loss_fn,
        e2e_train_state_init,
        make_train_step,
        north_star_e2e_config,
        predict_structure,
        stack_microbatches,
        synthetic_structure_batches,
    )

    # steps=1 on TPU: one optimizer step per device execution — the step is
    # tens of seconds of device time and longer single executions have
    # crashed the tunneled TPU worker; the timed call still fetches its
    # loss, so the measurement stays dispatch-proof. The CPU smoke config
    # (tiny shapes) exists so the bench always completes.
    steps = 1 if on_tpu else 2
    ecfg, crop, msa_rows = north_star_e2e_config(depth, smoke=not on_tpu)
    tcfg = TrainConfig(learning_rate=3e-4, grad_accum=1)
    dcfg = DataConfig(batch_size=1, max_len=crop, msa_rows=msa_rows, seed=0)

    batch = jax.device_put(
        next(stack_microbatches(synthetic_structure_batches(dcfg), 1))
    )
    state = e2e_train_state_init(jax.random.PRNGKey(0), ecfg, tcfg)

    if segments:
        # multi-execution step (training/segmented.py): same optimizer
        # step, chained short executions — the only way depth 48 runs
        # under the tunneled worker's single-execution time budget.
        # Timing stays dispatch-proof: grad_norm depends on every
        # segment's gradients, so fetching it forces the whole chain.
        from alphafold2_tpu.training import make_segmented_train_step

        seg_step = make_segmented_train_step(ecfg, tcfg, segments)
        state, metrics = seg_step(state, batch, jax.random.PRNGKey(1))
        np.asarray(metrics["grad_norm"])  # warmup: compiles + runs chain
        t0 = time.perf_counter()
        state, metrics = seg_step(state, batch, jax.random.PRNGKey(2))
        loss = float(np.asarray(metrics["loss"]))
        float(np.asarray(metrics["grad_norm"]))
        dt = time.perf_counter() - t0
        assert np.isfinite(loss), f"non-finite bench loss: {loss}"
        steps, steps_per_sec = 1, 1.0 / dt
        # per-piece cost analysis is not aggregated across the chain;
        # report honest nulls rather than a partial-program MFU
        flops_per_step, achieved, mfu = 0.0, 0.0, None
    else:
        step = make_train_step(ecfg, tcfg, loss_fn=e2e_loss_fn)

        def run_steps(state, batch, rng):
            def body(s, k):
                s2, metrics = step(s, batch, k)
                return s2, metrics["loss"]

            return jax.lax.scan(body, state, jax.random.split(rng, steps))

        # donate the state: without donation the input AND output copies of
        # (params + Adam state) are both live — ~8 GB at depth 48 — and the
        # north-star program does not fit; the warmup's output state feeds
        # the timed run
        compiled = (
            jax.jit(run_steps, donate_argnums=(0,))
            .lower(state, batch, jax.random.PRNGKey(1))
            .compile()
        )
        # warmup — and fetch, so compilation/dispatch cannot leak into timing
        state, losses = compiled(state, batch, jax.random.PRNGKey(1))
        np.asarray(losses)

        t0 = time.perf_counter()
        state, losses = compiled(state, batch, jax.random.PRNGKey(2))
        losses = np.asarray(losses)  # forces execution + download
        dt = time.perf_counter() - t0
        assert np.isfinite(losses).all(), f"non-finite bench losses: {losses}"

        steps_per_sec = steps / dt
        total_flops = _compiled_flops(compiled)
        flops_per_step = total_flops / steps if total_flops else 0.0
        achieved = flops_per_step * steps_per_sec
        mfu = achieved / _peak_flops(dev) if on_tpu and achieved else None

    # inference sec/protein: the predict flow (forward -> distogram -> MDS ->
    # sidechain -> refiner), BASELINE.md's second target metric
    infer = jax.jit(
        lambda p, s, m, mm, msk: predict_structure(
            p, ecfg, s, mask=msk, msa=m, msa_mask=mm
        )["refined"]
    )
    baseline = 1.0  # driver target: >=1 optimizer step/sec/chip (BASELINE.md)
    # the target is defined ON TPU at the north-star shapes; a CPU smoke
    # fallback must not read as progress against it (bench honesty —
    # VERDICT r1 weakness #3)
    vs_baseline = round(steps_per_sec / baseline, 4) if on_tpu else 0.0
    result = {
        "metric": f"train_end2end_steps_per_sec_crop{crop}_msa{msa_rows}"
                  f"_depth{depth}_{dev.platform}"
                  + (f"_seg{segments}" if segments else ""),
        **({"segments": segments} if segments else {}),
        "value": round(steps_per_sec, 4),
        "unit": "steps/sec",
        "vs_baseline": vs_baseline,
        **({} if on_tpu else
           {"note": f"non-TPU run ({dev.platform}) at smoke shapes; "
                    "vs_baseline deliberately 0 — the target is "
                    "TPU-defined"}),
        "sec_per_step": round(dt / steps, 3),
        "tflops_per_step": round(flops_per_step / 1e12, 2),
        "achieved_tflops_per_sec": round(achieved / 1e12, 2),
        "mfu": round(mfu, 4) if mfu is not None else None,
    }
    # print the train measurement BEFORE attempting inference: the parent
    # takes the LAST parseable stdout line, so if the inference forward
    # (a ~depth x 0.7 s single execution — tens of seconds at depth 48)
    # crashes the tunneled worker, the train numbers above still land
    print(json.dumps({**result, "inference_sec_per_protein": None,
                      "note_inference": "inference leg did not complete"}),
          flush=True)

    mb = jax.tree_util.tree_map(lambda t: t[0], batch)  # drop microbatch axis
    args = (state["params"], mb["seq"], mb["msa"], mb["msa_mask"], mb["mask"])
    np.asarray(infer(*args))  # compile + warmup
    t0 = time.perf_counter()
    np.asarray(infer(*args))
    infer_sec = time.perf_counter() - t0
    result["inference_sec_per_protein"] = round(infer_sec, 3)
    return result


if __name__ == "__main__":
    main()
