"""Benchmark: the NORTH-STAR workload — end-to-end structure training
(trunk -> distogram -> MDS -> sidechain lift -> SE(3) refiner -> Kabsch
RMSD loss) at crop=384, MSA=128, depth=48, bf16, reversible trunk, on one
chip — plus inference sec/protein (BASELINE.md operational target).

Prints JSON lines {"metric", "value", "unit", "vs_baseline", ...extras};
the LAST line is the result (the driver takes the last parseable stdout
line). These lines (and the driver's BENCH_*.json wrappers) are the
input format of the perf-regression gate — `python -m
alphafold2_tpu.telemetry.check --current <new> --baseline <BENCH_rNN>`
exits nonzero when a hot-path metric regressed beyond tolerance
(docs/OBSERVABILITY.md). Lines are printed incrementally — cheap CPU smoke first, then each
on-chip upgrade the moment it lands — so killing the process at any
instant after ~90 s still leaves a parseable metric (round-3 postmortem:
the artifact must be null-proof by construction). Total wall is clamped
to AF2_BENCH_BUDGET_SEC (default 1140 s).
The reference publishes no numbers (BASELINE.md), so vs_baseline is against
the driver-defined operational target of 1.0 optimizer step/sec/chip.
Extras: achieved TFLOP/s and MFU (analytic model-FLOP count from
utils/flops.py over the chip's peak — XLA cost analysis counts scan
bodies once and underreports the reversible/streamed trunk ~100x), and
inference sec/protein for the predict flow.

Methodology: K optimizer steps run INSIDE one jitted `lax.scan`, and the
per-step losses are fetched to the host before stopping the clock. This is
deliberate: on remotely-dispatched backends (the axon tunnel),
`block_until_ready` returns before device execution finishes, so a Python
step loop measures dispatch latency, not compute — fetching the results is
the only timing the backend cannot fake.
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np

# bf16 peak FLOP/s by TPU generation (public spec sheets)
_PEAK_FLOPS = (
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v6", 918e12),
    ("v4", 275e12),
    ("v3", 123e12),
)


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in _PEAK_FLOPS:
        if key in kind:
            return peak
    return 197e12  # default to v5e


def main():
    import argparse
    import os
    import subprocess
    import sys

    ap = argparse.ArgumentParser()
    ap.add_argument("--single-depth", type=int, default=None)
    ap.add_argument("--segments", type=int, default=0,
                    help="run the train step as this many reversible trunk "
                         "segments in SEPARATE device executions "
                         "(training/segmented.py) — the tunneled worker "
                         "kills single executions beyond ~60 s of device "
                         "time, which a monolithic depth-48 step exceeds")
    args = ap.parse_args()

    if args.single_depth is not None:
        dev = jax.devices()[0]
        print(json.dumps(_run(dev, dev.platform == "tpu", args.single_depth,
                              segments=args.segments)))
        return

    # The orchestrating parent NEVER initializes JAX: a wedged TPU tunnel
    # (observed after worker crashes) hangs backend init indefinitely, and
    # the parent must stay alive to fall back. A SUBPROCESS probe (a real
    # matmul, not just backend init — a wedged relay can enumerate devices
    # yet hang every execution) decides whether a healthy TPU is reachable.
    #
    # NULL-PROOF BY CONSTRUCTION (round-3 postmortem): the driver runs this
    # under its own ~20-minute timeout and parses the LAST JSON line of
    # stdout. Round 3's artifact was `parsed: null` because the probe-retry
    # window (1 h) plus the CPU-fallback timeout exceeded that budget — the
    # CPU line was never reached. The fix is ordering + arithmetic, not
    # heroics:
    #   1. the cheap CPU smoke runs FIRST (~50 s wall) and its JSON line is
    #      printed immediately — from that point on, a kill at ANY instant
    #      still leaves the driver a parseable metric;
    #   2. every later stage (probe retries, each TPU attempt) is clamped to
    #      a shared deadline derived from AF2_BENCH_BUDGET_SEC (default
    #      1140 s, conservative vs the ~20 min observed driver budget);
    #   3. each successful TPU attempt prints an upgraded line the moment it
    #      lands (depth-24 monolithic first, then depth-48 segmented with
    #      the depth-24 result embedded) — the last line on stdout is always
    #      the best measurement so far, never nothing.
    budget = float(os.environ.get("AF2_BENCH_BUDGET_SEC", 1140))
    deadline = time.monotonic() + budget

    def remaining():
        return deadline - time.monotonic()

    published = {"best": None}

    def publish(result):
        """Print best-so-far; the driver takes the LAST parseable line."""
        published["best"] = result
        print(json.dumps(result), flush=True)

    probe_script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "scripts", "tpu_probe.py")

    def probe_once(timeout=240):
        """-> 'healthy' | 'no-tpu' (deterministic, don't retry) |
        'transient' (timeout / crash before the platform print)."""
        try:
            probe = subprocess.run(
                [sys.executable, probe_script],
                capture_output=True, text=True, timeout=timeout,
            )
        except subprocess.TimeoutExpired:
            return "transient"
        if probe.returncode == 0 and "tpu-healthy" in probe.stdout:
            return "healthy"
        # backend init succeeded but the platform is not TPU: this host
        # has no TPU at all — retrying cannot change that
        if "platform:" in probe.stdout and "tpu" not in probe.stdout:
            return "no-tpu"
        return "transient"

    # Stage 1 — CPU smoke FIRST. ~50 s wall measured; after this line is on
    # stdout no failure mode (wedge, hang, driver kill) can null the
    # artifact. attempt() is defined below; it only needs closures above.

    # Depth ladder at the north-star crop/MSA (BASELINE.md config 5 is
    # depth 48). Ordering: depth 24 FIRST — it is known to complete within
    # the tunneled worker's ~60 s single-execution budget, while depth 48
    # (~96 s/step) has CRASHED the worker, and a crashed worker wedges the
    # relay for hours (every later backend init hangs). Securing the
    # shallower on-chip measurement before attempting the deeper one means
    # a depth-48 wedge costs the upgrade, not the whole measurement. The
    # terminal CPU smoke entry guarantees the driver always records a line.

    def attempt(depth, platform, timeout, disable_kernel=False, segments=0):
        env = dict(os.environ)
        if platform == "cpu":
            env.pop("PALLAS_AXON_POOL_IPS", None)
            env["JAX_PLATFORMS"] = "cpu"
        if disable_kernel:
            env["AF2_DISABLE_FLASH_KERNEL"] = "1"
        def salvage(stdout, label):
            # salvage a partial measurement: the worker prints the train
            # numbers BEFORE the inference leg, so a crash or hang there (a
            # long single forward execution) must not cost the whole attempt
            if isinstance(stdout, bytes):
                stdout = stdout.decode("utf-8", "replace")
            for line in reversed((stdout or "").strip().splitlines()):
                try:
                    partial = json.loads(line)
                except ValueError:
                    continue
                # a complete result (inference leg finished) that exited
                # nonzero afterwards is a teardown failure, not a partial
                # measurement — don't mislabel it
                if partial.get("inference_sec_per_protein") is None:
                    partial[label] = True
                return partial
            return None

        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--single-depth", str(depth),
                 *(["--segments", str(segments)] if segments else [])],
                capture_output=True, text=True, env=env, timeout=timeout,
            )
        except subprocess.TimeoutExpired as e:
            # the train row may already be on stdout (e.g. the inference
            # leg hung): keep it rather than discarding the measurement —
            # but still flag timed_out so the ladder stops driving a
            # now-suspect tunnel
            partial = salvage(e.stdout,
                              "worker_timed_out_after_train_measurement")
            if partial is not None:
                return partial, None, True
            # structured flag, not message-sniffing: stderr text may contain
            # its own unrelated "timed out" wording
            return None, f"depth-{depth} hit the {timeout}s timeout", True
        if proc.returncode != 0:
            partial = salvage(proc.stdout,
                              "worker_crashed_after_train_measurement")
            if partial is not None:
                return partial, None, False
            err = (proc.stderr or "").strip().splitlines()
            return None, (err[-1] if err else f"rc={proc.returncode}"), False
        for line in reversed(proc.stdout.strip().splitlines()):
            try:
                return json.loads(line), None, False
            except ValueError:
                continue
        return None, "subprocess succeeded but printed no JSON", False

    # Stage 1 — CPU smoke, off-tunnel (JAX_PLATFORMS=cpu subprocess). ~50 s
    # measured wall; once its line is printed the artifact cannot be null.
    cpu_result, cpu_err, _ = attempt(
        2, "cpu", timeout=max(90, min(420, remaining() - 30)))
    if cpu_result is not None:
        cpu_result["provisional"] = (
            "cpu smoke recorded first for null-proofing; superseded by a "
            "later line if an on-chip measurement lands")
        publish(cpu_result)
    else:
        print(f"CPU smoke failed: {cpu_err}", file=sys.stderr, flush=True)

    # Stage 2 — probe with retries, clamped so that a late healthy probe
    # still leaves room for one on-chip attempt. Round 2's artifact lost
    # its TPU measurement to a single failed probe, so transient failures
    # retry with backoff — but only within the budget.
    TPU_ATTEMPT_MIN = 420.0  # below this, compile + step cannot finish

    # Single-client tunnel lock, held for the rest of the process lifetime
    # (flock releases on exit): a second client beside a running
    # measurement deadlocks both and wedges the relay (scripts/tpu_lock.py).
    # If a watcher measurement is mid-flight, WAIT for it rather than
    # collide — the CPU line above keeps the artifact parseable throughout.
    import contextlib

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts"))
    from tpu_lock import tpu_lock

    _lock = contextlib.ExitStack()
    try:
        _lock.enter_context(tpu_lock(
            timeout=max(0.0, remaining() - TPU_ATTEMPT_MIN - 60)))
    except TimeoutError:
        note = ("TPU lock held by another local client for the whole bench "
                "budget; kept the CPU smoke line")
        print(note, file=sys.stderr, flush=True)
        if published["best"] is None:
            raise RuntimeError(note)
        final = {**published["best"], "fallback_reason": note}
        final.pop("provisional", None)
        publish(final)
        return

    status, n_probes = "transient", 0
    while remaining() > TPU_ATTEMPT_MIN + 60:
        # clamp the probe so a slow-but-healthy probe cannot eat the
        # headroom the attempt it unlocks would need — but floor it at
        # 60 s: post-wedge backend init + matmul takes ~50 s, and a
        # too-short probe would misread a recovering tunnel as transient
        status = probe_once(timeout=max(
            60, min(240, remaining() - TPU_ATTEMPT_MIN - 40)))
        n_probes += 1
        if status != "transient":
            break
        wait = min(480, 60 * n_probes)
        if remaining() - wait <= TPU_ATTEMPT_MIN + 60:
            break  # sleeping would leave no room for the re-probe +
            #        attempt the sleep is supposed to buy
        print(f"TPU probe {n_probes} failed; retrying in {wait:.0f}s "
              f"(budget remaining {remaining():.0f}s)",
              file=sys.stderr, flush=True)
        time.sleep(wait)
    if status != "healthy":
        note = ((f"TPU health probe failed {n_probes}x ({status}) within "
                 f"the {budget:.0f}s bench budget") if n_probes else
                (f"no TPU probe attempted: the {budget:.0f}s bench budget "
                 f"left no room for an on-chip attempt"))
        print(note, file=sys.stderr, flush=True)
        if published["best"] is None:
            raise RuntimeError(f"no TPU ({status}) and the CPU smoke "
                               f"failed: {cpu_err}")
        final = {**published["best"], "fallback_reason": note}
        final.pop("provisional", None)  # terminal: nothing supersedes it
        publish(final)
        return

    # Stage 3 — on-chip depth ladder, each attempt clamped to the deadline.
    # depth 24 runs monolithic FIRST (fits the worker's ~60 s
    # single-execution budget); depth 48 runs SEGMENTED
    # (training/segmented.py — the monolithic ~96 s step CRASHES the
    # tunneled worker, and a crashed worker wedges the relay for hours).
    # Securing the shallower measurement first means a depth-48 wedge
    # costs the upgrade, not the round.
    # The budget gates whether an attempt STARTS; the subprocess timeout
    # stays at the old generous backstop (>= 2400 s, only ever reached on
    # a hung tunnel). A tight internal timeout would SIGKILL the worker
    # mid-device-execution — the documented relay-wedge trigger (~9 h,
    # PERF.md). An attempt that overruns the driver budget instead gets
    # the PARENT killed by the driver while the grandchild finishes
    # safely orphaned; the incrementally-published lines above already
    # guarantee a parseable artifact in that case.
    TPU_ATTEMPT_BACKSTOP = 2400.0
    errors, depth24 = [], None
    for depth, segments in ((24, 0), (48, 4)):
        if remaining() - 20 < TPU_ATTEMPT_MIN:
            errors.append(f"depth-{depth} skipped: {remaining():.0f}s of "
                          f"budget left < {TPU_ATTEMPT_MIN:.0f}s minimum")
            break
        result, err, timed_out = attempt(
            depth, None, timeout=TPU_ATTEMPT_BACKSTOP + (600 if segments
                                                         else 0),
            segments=segments)
        if result is None and not timed_out:
            # non-timeout failure: retry once with the Pallas kernel
            # disabled, so a kernel-compile regression costs the fused
            # path, not the whole on-chip measurement
            errors.append(err)
            if remaining() - 20 >= TPU_ATTEMPT_MIN:
                result, err, timed_out = attempt(
                    depth, None, timeout=TPU_ATTEMPT_BACKSTOP,
                    disable_kernel=True, segments=segments)
                if result is not None:
                    result["flash_kernel_disabled"] = True
            else:
                err = (f"depth-{depth} kernel-disabled retry skipped: "
                       f"{remaining():.0f}s of budget left")
        if result is not None:
            if depth == 24:
                depth24 = result
            elif depth24 is not None:
                # one line carries both round-4 targets: depth-48
                # segmented steps/sec plus the depth-24 monolithic MFU row
                result["depth24_monolithic"] = depth24
            if errors:
                result["failed_attempts"] = "; ".join(
                    e[-120:] for e in errors)
            publish(result)  # lands the moment it exists — kill-safe
            if timed_out:
                # train row salvaged but the worker then hung: keep the
                # measurement, stop driving the suspect tunnel
                errors.append(f"depth-{depth} worker hung after the "
                              "train measurement")
                break
            continue
        errors.append(err)
        if timed_out:
            break  # wedged tunnel: later attempts would hang too

    best = published["best"]
    if best is None:
        raise RuntimeError(f"all bench attempts failed; last: "
                           f"{errors[-1] if errors else cpu_err}")
    if "_depth48" not in best.get("metric", ""):
        # the north-star depth did not land: mark the kept line a fallback
        # (PERF.md contract). A depth-48 result that needed the
        # kernel-disabled retry is NOT a fallback — flash_kernel_disabled
        # already records the degradation.
        final = dict(best)
        final.pop("provisional", None)  # terminal: nothing supersedes it
        final["fallback_from_depth"] = 48
        if errors:
            final["fallback_reason"] = errors[-1][-200:]
            final["failed_attempts"] = "; ".join(e[-120:] for e in errors)
        publish(final)


def _run(dev, on_tpu: bool, depth: int, segments: int = 0) -> dict:
    from alphafold2_tpu.training import (
        DataConfig,
        TrainConfig,
        e2e_loss_fn,
        e2e_train_state_init,
        make_train_step,
        north_star_e2e_config,
        predict_structure,
        stack_microbatches,
        synthetic_structure_batches,
    )

    # steps=1 on TPU: one optimizer step per device execution — the step is
    # tens of seconds of device time and longer single executions have
    # crashed the tunneled TPU worker; the timed call still fetches its
    # loss, so the measurement stays dispatch-proof. The CPU smoke config
    # (tiny shapes) exists so the bench always completes.
    steps = 1 if on_tpu else 2
    ecfg, crop, msa_rows = north_star_e2e_config(depth, smoke=not on_tpu)
    tcfg = TrainConfig(learning_rate=3e-4, grad_accum=1)
    dcfg = DataConfig(batch_size=1, max_len=crop, msa_rows=msa_rows, seed=0)

    batch = jax.device_put(
        next(stack_microbatches(synthetic_structure_batches(dcfg), 1))
    )
    state = e2e_train_state_init(jax.random.PRNGKey(0), ecfg, tcfg)

    if segments:
        # multi-execution step (training/segmented.py): same optimizer
        # step, chained short executions — the only way depth 48 runs
        # under the tunneled worker's single-execution time budget.
        # Timing stays dispatch-proof: grad_norm depends on every
        # segment's gradients, so fetching it forces the whole chain.
        from alphafold2_tpu.training import make_segmented_train_step

        seg_step = make_segmented_train_step(ecfg, tcfg, segments)
        state, metrics = seg_step(state, batch, jax.random.PRNGKey(1))
        np.asarray(metrics["grad_norm"])  # warmup: compiles + runs chain
        t0 = time.perf_counter()
        state, metrics = seg_step(state, batch, jax.random.PRNGKey(2))
        loss = float(np.asarray(metrics["loss"]))
        float(np.asarray(metrics["grad_norm"]))
        dt = time.perf_counter() - t0
        assert np.isfinite(loss), f"non-finite bench loss: {loss}"
        steps, steps_per_sec = 1, 1.0 / dt
    else:
        step = make_train_step(ecfg, tcfg, loss_fn=e2e_loss_fn)

        def run_steps(state, batch, rng):
            def body(s, k):
                s2, metrics = step(s, batch, k)
                return s2, metrics["loss"]

            return jax.lax.scan(body, state, jax.random.split(rng, steps))

        # donate the state: without donation the input AND output copies of
        # (params + Adam state) are both live — ~8 GB at depth 48 — and the
        # north-star program does not fit; the warmup's output state feeds
        # the timed run
        compiled = (
            jax.jit(run_steps, donate_argnums=(0,))
            .lower(state, batch, jax.random.PRNGKey(1))
            .compile()
        )
        # warmup — and fetch, so compilation/dispatch cannot leak into timing
        state, losses = compiled(state, batch, jax.random.PRNGKey(1))
        np.asarray(losses)

        t0 = time.perf_counter()
        state, losses = compiled(state, batch, jax.random.PRNGKey(2))
        losses = np.asarray(losses)  # forces execution + download
        dt = time.perf_counter() - t0
        assert np.isfinite(losses).all(), f"non-finite bench losses: {losses}"

        steps_per_sec = steps / dt

    # analytic model-FLOP count, shared by both branches (utils/flops.py):
    # XLA cost analysis counts scan bodies once — on the reversible/
    # streamed trunk it underreports ~100x and every MFU derived from it
    # is garbage — and never could aggregate the segmented chain at all
    from alphafold2_tpu.utils.flops import train_step_flops

    flops_per_step = train_step_flops(
        ecfg.model, 3 * crop, msa_rows, crop, grad_accum=tcfg.grad_accum,
    )
    achieved = flops_per_step * steps_per_sec
    mfu = achieved / _peak_flops(dev) if on_tpu else None

    # inference sec/protein: the predict flow (forward -> distogram -> MDS ->
    # sidechain -> refiner), BASELINE.md's second target metric
    infer = jax.jit(
        lambda p, s, m, mm, msk: predict_structure(
            p, ecfg, s, mask=msk, msa=m, msa_mask=mm
        )["refined"]
    )
    baseline = 1.0  # driver target: >=1 optimizer step/sec/chip (BASELINE.md)
    # the target is defined ON TPU at the north-star shapes; a CPU smoke
    # fallback must not read as progress against it (bench honesty —
    # VERDICT r1 weakness #3)
    vs_baseline = round(steps_per_sec / baseline, 4) if on_tpu else 0.0
    result = {
        "metric": f"train_end2end_steps_per_sec_crop{crop}_msa{msa_rows}"
                  f"_depth{depth}_{dev.platform}"
                  + (f"_seg{segments}" if segments else ""),
        **({"segments": segments} if segments else {}),
        "value": round(steps_per_sec, 4),
        "unit": "steps/sec",
        "vs_baseline": vs_baseline,
        **({} if on_tpu else
           {"note": f"non-TPU run ({dev.platform}) at smoke shapes; "
                    "vs_baseline deliberately 0 — the target is "
                    "TPU-defined"}),
        "sec_per_step": round(dt / steps, 3),
        "tflops_per_step": round(flops_per_step / 1e12, 2),
        "achieved_tflops_per_sec": round(achieved / 1e12, 2),
        "mfu": round(mfu, 4) if mfu is not None else None,
    }
    # print the train measurement BEFORE attempting inference: the parent
    # takes the LAST parseable stdout line, so if the inference forward
    # (a ~depth x 0.7 s single execution — tens of seconds at depth 48)
    # crashes the tunneled worker, the train numbers above still land
    print(json.dumps({**result, "inference_sec_per_protein": None,
                      "note_inference": "inference leg did not complete"}),
          flush=True)

    mb = jax.tree_util.tree_map(lambda t: t[0], batch)  # drop microbatch axis
    args = (state["params"], mb["seq"], mb["msa"], mb["msa_mask"], mb["mask"])
    np.asarray(infer(*args))  # compile + warmup
    t0 = time.perf_counter()
    np.asarray(infer(*args))
    infer_sec = time.perf_counter() - t0
    result["inference_sec_per_protein"] = round(infer_sec, 3)
    return result


if __name__ == "__main__":
    main()
