"""Benchmark: distogram-pretraining train-step throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no numbers (BASELINE.md), so the baseline is the
driver-defined operational target of 1.0 optimizer step/sec/chip; the
benchmarked workload is the train_pre path (reference train_pre.py) at
crop=256, depth=12, bf16 + per-layer remat on TPU (reduced shapes on CPU
fallback).

Methodology: K optimizer steps run INSIDE one jitted `lax.scan`, and the
per-step losses are fetched to the host before stopping the clock. This is
deliberate: on remotely-dispatched backends (the axon tunnel),
`block_until_ready` returns before device execution finishes, so a Python
step loop measures dispatch latency, not compute — fetching the results is
the only timing the backend cannot fake.
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np


def main():
    import jax.numpy as jnp

    from alphafold2_tpu.models import Alphafold2Config
    from alphafold2_tpu.training import (
        DataConfig,
        TrainConfig,
        make_train_step,
        stack_microbatches,
        synthetic_batches,
        train_state_init,
    )

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        dim, depth, crop, steps = 256, 12, 256, 20
    else:  # CPU smoke fallback so the bench always completes
        dim, depth, crop, steps = 64, 2, 64, 3

    cfg = Alphafold2Config(
        dim=dim,
        depth=depth,
        heads=8,
        dim_head=64,
        max_seq_len=max(2048, crop),
        dtype=jnp.bfloat16 if on_tpu else jnp.float32,
        # O(1) trunk activation memory: the depth-12 crop-256 backward
        # does not fit v5e HBM (15.75G) without it
        remat=on_tpu,
    )
    tcfg = TrainConfig(learning_rate=3e-4, grad_accum=1)
    dcfg = DataConfig(batch_size=1, max_len=crop, seed=0)

    batch = jax.device_put(next(stack_microbatches(synthetic_batches(dcfg), 1)))
    state = train_state_init(jax.random.PRNGKey(0), cfg, tcfg)
    step = make_train_step(cfg, tcfg)

    @jax.jit
    def run_steps(state, batch, rng):
        def body(s, k):
            s2, metrics = step(s, batch, k)
            return s2, metrics["loss"]

        return jax.lax.scan(body, state, jax.random.split(rng, steps))

    # warmup / compile — and fetch, so compilation cannot leak into timing
    _, losses = run_steps(state, batch, jax.random.PRNGKey(1))
    np.asarray(losses)

    t0 = time.perf_counter()
    _, losses = run_steps(state, batch, jax.random.PRNGKey(2))
    losses = np.asarray(losses)  # forces execution + download
    dt = time.perf_counter() - t0
    assert np.isfinite(losses).all()

    steps_per_sec = steps / dt
    baseline = 1.0  # driver target: >=1 optimizer step/sec/chip (BASELINE.md)
    print(
        json.dumps(
            {
                "metric": f"train_pre_steps_per_sec_crop{crop}_depth{depth}_"
                          f"{jax.devices()[0].platform}",
                "value": round(steps_per_sec, 4),
                "unit": "steps/sec",
                "vs_baseline": round(steps_per_sec / baseline, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
