// TPU-native host runtime: threaded prefetch batch loader + PDB codec.
//
// The reference's data path is Python-side sidechainnet iteration with
// dynamic shapes (reference train_pre.py:44-55) and its PDB I/O shells out
// to curl + mdtraj (reference utils.py:83-149). Here the host-side hot
// paths are native:
//
//   * a prefetching batch loader: worker threads shuffle, crop/pad to
//     static shapes, and assemble (seq, mask, coords) batches into a
//     bounded queue entirely outside the Python GIL, so the accelerator
//     never waits on host batch assembly;
//   * a fixed-column PDB ATOM-record codec (parse + write), the text
//     format's cost center when loading thousands of structures.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in the image).

#include <atomic>
#include <charconv>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Prefetch loader
// ---------------------------------------------------------------------------

struct Af2Batch {
  // COMPACT layout at the batch's own length: (batch, bucket_len[, ...]).
  // bucket_len == max_len in single-shape mode; bucketed batches carry no
  // padding columns beyond their bucket, so queue memory and the next()
  // memcpy scale with the bucket, not the largest bucket.
  std::vector<int32_t> seq;    // (batch, bucket_len)
  std::vector<uint8_t> mask;   // (batch, bucket_len)
  std::vector<float> coords;   // (batch, bucket_len, atoms_per_res, 3)
  int bucket_len = 0;
};

struct Af2Loader {
  // dataset (borrowed copies — the loader owns its memory after create)
  std::vector<int32_t> seqs;      // concatenated residue tokens
  std::vector<int64_t> offsets;   // n_seqs+1 prefix offsets into seqs
  std::vector<float> coords;      // aligned with seqs: atoms_per_res*3 per residue
  int n_seqs = 0;
  int batch = 1;
  int max_len = 128;
  int atoms_per_res = 14;
  int pad_token = 20;
  // ascending static length buckets (empty = single-shape mode). A protein
  // goes to the smallest bucket holding it (random-cropped to the largest
  // otherwise); batches are emitted per bucket, with buffers laid out at
  // max_len (== buckets.back()) and bucket_len marking the valid columns.
  std::vector<int32_t> buckets;

  // queue
  size_t capacity = 4;
  std::deque<Af2Batch> queue;
  std::mutex mu;
  std::condition_variable cv_push, cv_pop;
  std::atomic<bool> stop{false};
  std::vector<std::thread> workers;
  uint64_t seed = 0;

  void fill_row(std::mt19937_64& rng, Af2Batch& b, int i, int idx) {
    const int row_len = b.bucket_len;
    int64_t beg = offsets[idx], end = offsets[idx + 1];
    int len = (int)(end - beg);
    int start = 0;
    if (len > row_len) {  // random crop
      std::uniform_int_distribution<int> off(0, len - row_len);
      start = off(rng);
      len = row_len;
    }
    std::memcpy(&b.seq[(size_t)i * row_len], &seqs[beg + start],
                sizeof(int32_t) * len);
    std::memset(&b.mask[(size_t)i * row_len], 1, len);
    std::memcpy(&b.coords[(size_t)i * row_len * atoms_per_res * 3],
                &coords[(beg + start) * atoms_per_res * 3],
                sizeof(float) * (size_t)len * atoms_per_res * 3);
  }

  Af2Batch fresh_batch(int bucket_len_) {
    Af2Batch b;
    b.seq.assign((size_t)batch * bucket_len_, pad_token);
    b.mask.assign((size_t)batch * bucket_len_, 0);
    b.coords.assign((size_t)batch * bucket_len_ * atoms_per_res * 3, 0.0f);
    b.bucket_len = bucket_len_;
    return b;
  }

  void push(Af2Batch&& b) {
    std::unique_lock<std::mutex> lk(mu);
    cv_push.wait(lk, [&] { return stop.load() || queue.size() < capacity; });
    if (stop.load()) return;
    queue.push_back(std::move(b));
    cv_pop.notify_one();
  }

  void worker(int wid) {
    std::mt19937_64 rng(seed ^ (0x9e3779b97f4a7c15ULL * (wid + 1)));
    std::uniform_int_distribution<int> pick(0, n_seqs - 1);
    if (buckets.empty()) {
      while (!stop.load()) {
        Af2Batch b = fresh_batch(max_len);
        for (int i = 0; i < batch; ++i) fill_row(rng, b, i, pick(rng));
        push(std::move(b));
      }
      return;
    }
    // bucketed mode: accumulate picked proteins per bucket (worker-local —
    // no cross-thread pending state), emit when a bucket fills
    std::vector<std::vector<int>> pending(buckets.size());
    while (!stop.load()) {
      int idx = pick(rng);
      int len = (int)(offsets[idx + 1] - offsets[idx]);
      size_t bi = buckets.size() - 1;
      for (size_t k = 0; k < buckets.size(); ++k)
        if (len <= buckets[k]) { bi = k; break; }
      pending[bi].push_back(idx);
      if ((int)pending[bi].size() < batch) continue;
      Af2Batch b = fresh_batch(buckets[bi]);
      for (int i = 0; i < batch; ++i)
        fill_row(rng, b, i, pending[bi][i]);
      pending[bi].clear();
      push(std::move(b));
    }
  }
};

// buckets: ascending static lengths, or n_buckets == 0 for single-shape
// mode; bucketed loaders require max_len == buckets[n_buckets-1] (buffers
// are laid out at max_len).
void* af2_loader_create2(const int32_t* seqs, const int64_t* offsets,
                         int n_seqs, const float* coords, int atoms_per_res,
                         int batch, int max_len, int pad_token, uint64_t seed,
                         int n_threads, int queue_capacity,
                         const int32_t* buckets, int n_buckets) {
  if (n_seqs <= 0 || batch <= 0 || max_len <= 0) return nullptr;
  if (n_buckets > 0) {
    for (int i = 1; i < n_buckets; ++i)
      if (buckets[i] <= buckets[i - 1]) return nullptr;  // must ascend
    if (buckets[n_buckets - 1] != max_len) return nullptr;
  }
  auto* L = new Af2Loader();
  int64_t total = offsets[n_seqs];
  L->seqs.assign(seqs, seqs + total);
  L->offsets.assign(offsets, offsets + n_seqs + 1);
  L->coords.assign(coords, coords + total * atoms_per_res * 3);
  L->n_seqs = n_seqs;
  L->batch = batch;
  L->max_len = max_len;
  L->atoms_per_res = atoms_per_res;
  L->pad_token = pad_token;
  L->seed = seed;
  L->capacity = queue_capacity > 0 ? queue_capacity : 4;
  if (n_buckets > 0) L->buckets.assign(buckets, buckets + n_buckets);
  int nt = n_threads > 0 ? n_threads : 1;
  for (int i = 0; i < nt; ++i)
    L->workers.emplace_back([L, i] { L->worker(i); });
  return L;
}

void* af2_loader_create(const int32_t* seqs, const int64_t* offsets,
                        int n_seqs, const float* coords, int atoms_per_res,
                        int batch, int max_len, int pad_token, uint64_t seed,
                        int n_threads, int queue_capacity) {
  return af2_loader_create2(seqs, offsets, n_seqs, coords, atoms_per_res,
                            batch, max_len, pad_token, seed, n_threads,
                            queue_capacity, nullptr, 0);
}

// Returns the batch's bucket length (== max_len in single-shape mode).
// Output is written COMPACT at the returned length — row i of seq/mask
// starts at i*bucket_len, coords at i*bucket_len*atoms*3 — so callers size
// buffers for max_len but reshape the filled prefix to (batch, bucket_len).
int af2_loader_next(void* handle, int32_t* seq_out, uint8_t* mask_out,
                    float* coords_out) {
  auto* L = static_cast<Af2Loader*>(handle);
  Af2Batch b;
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->cv_pop.wait(lk, [&] { return !L->queue.empty(); });
    b = std::move(L->queue.front());
    L->queue.pop_front();
    L->cv_push.notify_one();
  }
  std::memcpy(seq_out, b.seq.data(), b.seq.size() * sizeof(int32_t));
  std::memcpy(mask_out, b.mask.data(), b.mask.size());
  std::memcpy(coords_out, b.coords.data(), b.coords.size() * sizeof(float));
  return b.bucket_len;
}

void af2_loader_destroy(void* handle) {
  auto* L = static_cast<Af2Loader*>(handle);
  {
    // hold the mutex across the store+notify: a worker between its
    // predicate check and blocking would otherwise miss the wakeup and
    // join() would hang
    std::lock_guard<std::mutex> lk(L->mu);
    L->stop.store(true);
    L->cv_push.notify_all();
  }
  for (auto& t : L->workers) t.join();
  delete L;
}

// ---------------------------------------------------------------------------
// PDB codec (fixed-column ATOM records)
// ---------------------------------------------------------------------------

static inline float field_f(const char* line, int beg, int len) {
  // std::from_chars: locale-INDEPENDENT ('.' decimal always) — atof would
  // silently truncate fractions under an LC_NUMERIC comma-decimal locale
  const char* b = line + beg;
  const char* e = b + len;
  while (b < e && *b == ' ') ++b;
  float v = 0.0f;
#if defined(__cpp_lib_to_chars) && __cpp_lib_to_chars >= 201611L
  std::from_chars(b, e, v);
#else
  // GCC 10's libstdc++ ships integer from_chars only (float overloads
  // landed in GCC 11). PDB float fields are plain fixed-point ("%8.3f",
  // no exponent, no locale formatting), so a hand-rolled parse is exact
  // enough and stays locale-independent.
  bool neg = false;
  if (b < e && (*b == '-' || *b == '+')) { neg = (*b == '-'); ++b; }
  double acc = 0.0;
  while (b < e && *b >= '0' && *b <= '9') { acc = acc * 10.0 + (*b - '0'); ++b; }
  if (b < e && *b == '.') {
    ++b;
    double scale = 0.1;
    while (b < e && *b >= '0' && *b <= '9') { acc += (*b - '0') * scale; scale *= 0.1; ++b; }
  }
  v = static_cast<float>(neg ? -acc : acc);
#endif
  return v;
}

static inline int field_i(const char* line, int beg, int len) {
  char buf[16];
  std::memcpy(buf, line + beg, len);
  buf[len] = 0;
  return atoi(buf);
}

// Parse ATOM records (first model). Per atom writes: xyz (3 floats),
// res_seq (int32), B-factor (1 float — carries per-residue confidence,
// geometry/pdb.py convention), and 4-char atom name + 3-char residue name +
// 1-char chain into the names buffer (8 bytes/atom: name[4], res3[3],
// chain[1]). Returns number of atoms parsed (capped at max_atoms).
int af2_parse_pdb(const char* text, int64_t text_len, int max_atoms,
                  float* xyz_out, int32_t* res_seq_out, float* bfactor_out,
                  char* names_out) {
  int n = 0;
  const char* p = text;
  const char* end = text + text_len;
  while (p < end && n < max_atoms) {
    const char* nl = (const char*)memchr(p, '\n', end - p);
    size_t linelen = nl ? (size_t)(nl - p) : (size_t)(end - p);
    if (linelen >= 6 && std::strncmp(p, "ENDMDL", 6) == 0) break;
    if (linelen >= 54 && std::strncmp(p, "ATOM", 4) == 0 &&
        (p[4] == ' ' || p[4] == '\t')) {
      xyz_out[n * 3 + 0] = field_f(p, 30, 8);
      xyz_out[n * 3 + 1] = field_f(p, 38, 8);
      xyz_out[n * 3 + 2] = field_f(p, 46, 8);
      res_seq_out[n] = field_i(p, 22, 4);
      bfactor_out[n] = linelen >= 66 ? field_f(p, 60, 6) : 0.0f;
      std::memcpy(names_out + n * 8 + 0, p + 12, 4);  // atom name
      std::memcpy(names_out + n * 8 + 4, p + 17, 3);  // res name
      names_out[n * 8 + 7] = p[21];                   // chain id
      ++n;
    }
    if (!nl) break;
    p = nl + 1;
  }
  return n;
}

// Write ATOM records into `out` (caller sizes it at >= 82*(n_atoms+1)).
// names layout as af2_parse_pdb; bfactor may be null (writes 0.00).
// Returns bytes written.
int64_t af2_write_pdb(const float* xyz, const int32_t* res_seq,
                      const float* bfactor, const char* names, int n_atoms,
                      char* out, int64_t out_cap) {
  int64_t w = 0;
  for (int i = 0; i < n_atoms; ++i) {
    if (w + 82 > out_cap) return -1;
    char name[5] = {0}, res3[4] = {0};
    std::memcpy(name, names + i * 8, 4);
    std::memcpy(res3, names + i * 8 + 4, 3);
    char chain = names[i * 8 + 7];
    // columns (1-based): serial 7-11, name 13-16, altLoc 17 (blank),
    // resName 18-20, chain 22, resSeq 23-26, x/y/z from 31 — matching the
    // fixed-column reads in af2_parse_pdb and geometry/pdb.py
    w += std::snprintf(
        out + w, out_cap - w,
        "ATOM  %5d %-4s %3s %c%4d    %8.3f%8.3f%8.3f%6.2f%6.2f\n",
        i + 1, name, res3, chain ? chain : 'A', res_seq[i],
        xyz[i * 3 + 0], xyz[i * 3 + 1], xyz[i * 3 + 2], 1.0,
        bfactor ? bfactor[i] : 0.0f);
  }
  if (w + 4 <= out_cap) w += std::snprintf(out + w, out_cap - w, "END\n");
  return w;
}

}  // extern "C"
