// TPU-native host runtime: threaded prefetch batch loader + PDB codec.
//
// The reference's data path is Python-side sidechainnet iteration with
// dynamic shapes (reference train_pre.py:44-55) and its PDB I/O shells out
// to curl + mdtraj (reference utils.py:83-149). Here the host-side hot
// paths are native:
//
//   * a prefetching batch loader: worker threads shuffle, crop/pad to
//     static shapes, and assemble (seq, mask, coords) batches into a
//     bounded queue entirely outside the Python GIL, so the accelerator
//     never waits on host batch assembly;
//   * a fixed-column PDB ATOM-record codec (parse + write), the text
//     format's cost center when loading thousands of structures.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in the image).

#include <atomic>
#include <charconv>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Prefetch loader
// ---------------------------------------------------------------------------

struct Af2Batch {
  std::vector<int32_t> seq;    // (batch, max_len)
  std::vector<uint8_t> mask;   // (batch, max_len)
  std::vector<float> coords;   // (batch, max_len, atoms_per_res, 3)
};

struct Af2Loader {
  // dataset (borrowed copies — the loader owns its memory after create)
  std::vector<int32_t> seqs;      // concatenated residue tokens
  std::vector<int64_t> offsets;   // n_seqs+1 prefix offsets into seqs
  std::vector<float> coords;      // aligned with seqs: atoms_per_res*3 per residue
  int n_seqs = 0;
  int batch = 1;
  int max_len = 128;
  int atoms_per_res = 14;
  int pad_token = 20;

  // queue
  size_t capacity = 4;
  std::deque<Af2Batch> queue;
  std::mutex mu;
  std::condition_variable cv_push, cv_pop;
  std::atomic<bool> stop{false};
  std::vector<std::thread> workers;
  uint64_t seed = 0;

  void worker(int wid) {
    std::mt19937_64 rng(seed ^ (0x9e3779b97f4a7c15ULL * (wid + 1)));
    std::uniform_int_distribution<int> pick(0, n_seqs - 1);
    while (!stop.load()) {
      Af2Batch b;
      b.seq.assign((size_t)batch * max_len, pad_token);
      b.mask.assign((size_t)batch * max_len, 0);
      b.coords.assign((size_t)batch * max_len * atoms_per_res * 3, 0.0f);
      for (int i = 0; i < batch; ++i) {
        int idx = pick(rng);
        int64_t beg = offsets[idx], end = offsets[idx + 1];
        int len = (int)(end - beg);
        int start = 0;
        if (len > max_len) {  // random crop
          std::uniform_int_distribution<int> off(0, len - max_len);
          start = off(rng);
          len = max_len;
        }
        std::memcpy(&b.seq[(size_t)i * max_len], &seqs[beg + start],
                    sizeof(int32_t) * len);
        std::memset(&b.mask[(size_t)i * max_len], 1, len);
        std::memcpy(&b.coords[(size_t)i * max_len * atoms_per_res * 3],
                    &coords[(beg + start) * atoms_per_res * 3],
                    sizeof(float) * (size_t)len * atoms_per_res * 3);
      }
      std::unique_lock<std::mutex> lk(mu);
      cv_push.wait(lk, [&] { return stop.load() || queue.size() < capacity; });
      if (stop.load()) return;
      queue.push_back(std::move(b));
      cv_pop.notify_one();
    }
  }
};

void* af2_loader_create(const int32_t* seqs, const int64_t* offsets,
                        int n_seqs, const float* coords, int atoms_per_res,
                        int batch, int max_len, int pad_token, uint64_t seed,
                        int n_threads, int queue_capacity) {
  if (n_seqs <= 0 || batch <= 0 || max_len <= 0) return nullptr;
  auto* L = new Af2Loader();
  int64_t total = offsets[n_seqs];
  L->seqs.assign(seqs, seqs + total);
  L->offsets.assign(offsets, offsets + n_seqs + 1);
  L->coords.assign(coords, coords + total * atoms_per_res * 3);
  L->n_seqs = n_seqs;
  L->batch = batch;
  L->max_len = max_len;
  L->atoms_per_res = atoms_per_res;
  L->pad_token = pad_token;
  L->seed = seed;
  L->capacity = queue_capacity > 0 ? queue_capacity : 4;
  int nt = n_threads > 0 ? n_threads : 1;
  for (int i = 0; i < nt; ++i)
    L->workers.emplace_back([L, i] { L->worker(i); });
  return L;
}

void af2_loader_next(void* handle, int32_t* seq_out, uint8_t* mask_out,
                     float* coords_out) {
  auto* L = static_cast<Af2Loader*>(handle);
  Af2Batch b;
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->cv_pop.wait(lk, [&] { return !L->queue.empty(); });
    b = std::move(L->queue.front());
    L->queue.pop_front();
    L->cv_push.notify_one();
  }
  std::memcpy(seq_out, b.seq.data(), b.seq.size() * sizeof(int32_t));
  std::memcpy(mask_out, b.mask.data(), b.mask.size());
  std::memcpy(coords_out, b.coords.data(), b.coords.size() * sizeof(float));
}

void af2_loader_destroy(void* handle) {
  auto* L = static_cast<Af2Loader*>(handle);
  {
    // hold the mutex across the store+notify: a worker between its
    // predicate check and blocking would otherwise miss the wakeup and
    // join() would hang
    std::lock_guard<std::mutex> lk(L->mu);
    L->stop.store(true);
    L->cv_push.notify_all();
  }
  for (auto& t : L->workers) t.join();
  delete L;
}

// ---------------------------------------------------------------------------
// PDB codec (fixed-column ATOM records)
// ---------------------------------------------------------------------------

static inline float field_f(const char* line, int beg, int len) {
  // std::from_chars: locale-INDEPENDENT ('.' decimal always) — atof would
  // silently truncate fractions under an LC_NUMERIC comma-decimal locale
  const char* b = line + beg;
  const char* e = b + len;
  while (b < e && *b == ' ') ++b;
  float v = 0.0f;
  std::from_chars(b, e, v);
  return v;
}

static inline int field_i(const char* line, int beg, int len) {
  char buf[16];
  std::memcpy(buf, line + beg, len);
  buf[len] = 0;
  return atoi(buf);
}

// Parse ATOM records (first model). Per atom writes: xyz (3 floats),
// res_seq (int32), and 4-char atom name + 3-char residue name + 1-char
// chain into the names buffer (8 bytes/atom: name[4], res3[3], chain[1]).
// Returns number of atoms parsed (capped at max_atoms).
int af2_parse_pdb(const char* text, int64_t text_len, int max_atoms,
                  float* xyz_out, int32_t* res_seq_out, char* names_out) {
  int n = 0;
  const char* p = text;
  const char* end = text + text_len;
  while (p < end && n < max_atoms) {
    const char* nl = (const char*)memchr(p, '\n', end - p);
    size_t linelen = nl ? (size_t)(nl - p) : (size_t)(end - p);
    if (linelen >= 6 && std::strncmp(p, "ENDMDL", 6) == 0) break;
    if (linelen >= 54 && std::strncmp(p, "ATOM", 4) == 0 &&
        (p[4] == ' ' || p[4] == '\t')) {
      xyz_out[n * 3 + 0] = field_f(p, 30, 8);
      xyz_out[n * 3 + 1] = field_f(p, 38, 8);
      xyz_out[n * 3 + 2] = field_f(p, 46, 8);
      res_seq_out[n] = field_i(p, 22, 4);
      std::memcpy(names_out + n * 8 + 0, p + 12, 4);  // atom name
      std::memcpy(names_out + n * 8 + 4, p + 17, 3);  // res name
      names_out[n * 8 + 7] = p[21];                   // chain id
      ++n;
    }
    if (!nl) break;
    p = nl + 1;
  }
  return n;
}

// Write ATOM records into `out` (caller sizes it at >= 82*(n_atoms+1)).
// names layout as af2_parse_pdb. Returns bytes written.
int64_t af2_write_pdb(const float* xyz, const int32_t* res_seq,
                      const char* names, int n_atoms, char* out,
                      int64_t out_cap) {
  int64_t w = 0;
  for (int i = 0; i < n_atoms; ++i) {
    if (w + 82 > out_cap) return -1;
    char name[5] = {0}, res3[4] = {0};
    std::memcpy(name, names + i * 8, 4);
    std::memcpy(res3, names + i * 8 + 4, 3);
    char chain = names[i * 8 + 7];
    // columns (1-based): serial 7-11, name 13-16, altLoc 17 (blank),
    // resName 18-20, chain 22, resSeq 23-26, x/y/z from 31 — matching the
    // fixed-column reads in af2_parse_pdb and geometry/pdb.py
    w += std::snprintf(
        out + w, out_cap - w,
        "ATOM  %5d %-4s %3s %c%4d    %8.3f%8.3f%8.3f%6.2f%6.2f\n",
        i + 1, name, res3, chain ? chain : 'A', res_seq[i],
        xyz[i * 3 + 0], xyz[i * 3 + 1], xyz[i * 3 + 2], 1.0, 0.0);
  }
  if (w + 4 <= out_cap) w += std::snprintf(out + w, out_cap - w, "END\n");
  return w;
}

}  // extern "C"
